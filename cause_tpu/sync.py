"""Anti-entropy sync: converge replicas over any byte stream.

The reference's distributed story is "the CRDT is the protocol" — any
transport that moves immutable nodes between sites converges
(reference: README.md:5), with actual p2p sync transports left as a
roadmap wish (README.md:237-238). cause_tpu ships one: version-vector
delta sync at the collection level.

The yarn cache (per-site, time-sorted node lists — shared.cljc:64-65)
IS a version vector: ``{site: newest ts}``. A sync round is then

1. exchange version vectors (one small frame each way);
2. send the nodes the peer hasn't seen (everything in each yarn above
   the peer's entry — per-site suffixes, straight off the yarn cache);
3. apply the received delta as a merge (all the append-only /
   cause-must-exist / uuid guards come from the normal merge path, so
   a malicious or corrupt delta is rejected exactly like a bad
   ``insert``).

Deltas assume the per-site prefix property (a replica holding a site's
node at ts T holds all of that site's nodes below T), which this
protocol itself preserves — anything else (e.g. a weft-truncated past)
fails cause-must-exist and triggers the full-bag fallback frame.

Frames are length-prefixed JSON (serde's tagged encoding), so the same
session runs over sockets, pipes, files, or an in-memory loopback —
and the payloads are exactly the "bag of nodes" the reference
checkpoints (README.md:19).
"""

from __future__ import annotations

import json
import struct
import threading
import zlib
from typing import Dict, Optional, Tuple

from . import chaos as _chaos
from . import obs
from .collections import shared as s
from . import serde
from .obs import costmodel as _cm
from .obs import lag as _lag
from .obs import semantic as _sem
from .obs import xtrace as _xtrace

__all__ = [
    "version_vector",
    "delta_nodes",
    "shadow",
    "apply_delta",
    "payload_checksum",
    "validate_node_items",
    "is_quarantined",
    "any_quarantined",
    "quarantined",
    "note_reject",
    "note_clean",
    "readmit",
    "quarantine_reset",
    "send_frame",
    "recv_frame",
    "exchange_frame",
    "sync_stream",
    "sync_pair",
    "sync_base_pair",
]

_HDR = struct.Struct("!I")
MAX_FRAME = 1 << 28  # 256 MB: fail loudly on a corrupt length prefix
# how long a completed receive waits for our own send to drain before
# declaring the peer wedged (generous: full-bag frames on slow uplinks
# legitimately take minutes)
SEND_DRAIN_TIMEOUT = 600.0
# consecutive rejected payloads from one peer before it is quarantined
# out of delta exchanges (and device waves) until a clean validated
# full-bag resync re-admits it
QUARANTINE_AFTER = 3


def version_vector(handle) -> Dict[str, list]:
    """{site: [ts, tx_index] of the newest node} off the yarn cache.
    The tx index matters: ids are (ts, site, tx) and one transaction
    mints same-ts runs, so a ts-only vector would hide a peer stuck
    mid-run (same ts, lower tx) and silently never heal it."""
    return {
        site: [yarn[-1][0][0], yarn[-1][0][2]]
        for site, yarn in handle.ct.yarns.items()
        if yarn
    }


def delta_nodes(handle, peer_vv: Dict[str, list]) -> dict:
    """The nodes the peer hasn't seen: each yarn's suffix above the
    peer's version-vector entry (binary search per yarn — yarns are
    time-sorted; entries compare as (ts, tx))."""
    out = {}
    for site, yarn in handle.ct.yarns.items():
        h = peer_vv.get(site)
        horizon = (int(h[0]), int(h[1])) if h else (-1, -1)
        if not yarn or (yarn[-1][0][0], yarn[-1][0][2]) <= horizon:
            continue
        lo, hi = 0, len(yarn)
        while lo < hi:
            mid = (lo + hi) // 2
            if (yarn[mid][0][0], yarn[mid][0][2]) <= horizon:
                lo = mid + 1
            else:
                hi = mid
        for nid, cause, value in yarn[lo:]:
            out[nid] = (cause, value)
    return out


def shadow(handle, nodes: dict):
    """A same-type handle carrying exactly ``nodes`` — the merge-ready
    container for a received delta. Not a valid standalone tree (causes
    may point outside); only feed it to ``handle.merge``, which unions
    and validates against the receiver."""
    return type(handle)(handle.ct.evolve(nodes=dict(nodes)))


def apply_delta(handle, nodes: dict, _count_as_delta: bool = True):
    """Merge a received delta into ``handle`` (no-op for an empty
    delta). Raises CausalError exactly like a local merge would on
    append-only conflicts, uuid mismatch, or missing causes.
    ``_count_as_delta=False`` is the full-bag call sites' spelling:
    a resend of the whole bag must not count as a delta round in the
    semantic degradation rate.

    Path choice matters on the default pure weaver: ``merge`` replays
    the delta incrementally (O(delta x doc) — right for anti-entropy's
    steady state of small deltas into large docs), while ``merge_many``
    does one union + one full reweave (O(doc^2) pure, but the fast
    path under the native/jax backends and for bulk deltas). Small
    deltas on the pure backend take the incremental path; everything
    else takes the one-pass union."""
    if not nodes:
        return handle
    sh = shadow(handle, nodes)
    incremental = (handle.ct.weaver == "pure"
                   and len(nodes) * 8 < len(handle.ct.nodes))
    merged = handle.merge(sh) if incremental else handle.merge_many([sh])
    # emitted only AFTER the merge validated: a rejected delta is a
    # full-bag round, not a delta round — recording it before the
    # raise would make every degraded round count twice and understate
    # the full_bag_rate the fleet CLI reports
    if _count_as_delta and obs.enabled():
        _sem.sync_applied(len(nodes),
                          "incremental" if incremental else "union",
                          uuid=handle.ct.uuid)
        # divergence evidence for the cost model: these ops accrue to
        # the document and drain into its NEXT wave.cost event, so
        # per-wave cost sits next to the sync layer's own accounting
        _cm.note_delta_ops(handle.ct.uuid, len(nodes))
    if obs.enabled():
        # convergence-lag tracer, ingest side (delta AND full-bag
        # re-applies: either way these nodes just became visible on
        # this replica): ops stamped at creation in-process record
        # their apply lag against the receiving replica; foreign ops
        # are stamped now — ingest IS their local creation time
        _lag.ops_applied(handle.ct.uuid, nodes.keys(),
                         replica=handle.ct.site_id)
        # journey hop (PR 19): the delta's ops just became visible on
        # this replica — one "apply" hop per distinct trace riding
        # the batch (remote-apply in the per-hop SLO decomposition)
        for tr in _xtrace.traces_of(nodes.keys()):
            _xtrace.hop("apply", tr, uuid=str(handle.ct.uuid),
                        replica=str(handle.ct.site_id),
                        ops=len(nodes))
    return merged


# ---------------------------------------------- validate-before-apply
#
# PR 11: a sync payload crosses a trust boundary (a socket, a pipe, a
# chaos-mangled loopback). Before this layer existed, a corrupted or
# truncated payload either raised a bare TypeError deep inside the
# weave (decode succeeded, the merge choked on a malformed id) or —
# worse — merged cleanly and poisoned the document. Every ingest now
# validates STRUCTURE (triple shape, id types, canonical sort order,
# duplicate ids) and, on framed transports, a CRC32 checksum, and a
# failing payload is REJECTED at the boundary with a ``sync.reject``
# event: the document is untouched and the round degrades to the
# full-bag resync it already knew how to run.


def payload_checksum(encoded_items: list) -> int:
    """CRC32 over the canonical JSON of an encoded node-items payload
    (``serde.encode_node_items`` output) — the integrity tag delta and
    full frames carry as ``crc``."""
    blob = json.dumps(encoded_items, separators=(",", ":"),
                      allow_nan=False).encode()
    return zlib.crc32(blob) & 0xFFFFFFFF


def _valid_id(enc) -> bool:
    return (isinstance(enc, (list, tuple)) and len(enc) == 3
            and isinstance(enc[0], int) and not isinstance(enc[0], bool)
            and isinstance(enc[1], str) and enc[1] != ""
            and isinstance(enc[2], int) and not isinstance(enc[2], bool)
            and enc[0] >= 0 and enc[2] >= 0)


def validate_node_items(data) -> None:
    """Structural validation of an encoded node-items payload, raising
    ``CausalError`` (causes ``{"payload-invalid"}``) on the first
    violation. Checks per item: ``[id, cause, value]`` triple shape,
    id = ``[ts >= 0, nonempty site str, tx >= 0]``, id-shaped causes
    well-formed; payload-wide: ids strictly increasing (the canonical
    ``encode_node_items`` sort — a reordered payload was tampered
    with) and therefore unique (a duplicated id ditto)."""

    def bad(why: str, index: Optional[int] = None):
        info = {"causes": {"payload-invalid"}, "why": why}
        if index is not None:
            info["index"] = index
        return s.CausalError("sync payload rejected", info)

    if not isinstance(data, list):
        raise bad("payload is not a list")
    prev = None
    for i, item in enumerate(data):
        if not isinstance(item, (list, tuple)) or len(item) != 3:
            raise bad("node triple malformed", i)
        enc_id, enc_cause, _value = item
        if not _valid_id(enc_id):
            raise bad("node id malformed", i)
        # a cause is an id (positional list) or a tagged value (map
        # keys); a LIST-shaped cause must be id-shaped — anything else
        # would decode into garbage the weave chokes on later
        if isinstance(enc_cause, (list, tuple)) and not _valid_id(
                enc_cause):
            raise bad("cause id malformed", i)
        key = (enc_id[0], enc_id[1], enc_id[2])
        if prev is not None and key <= prev:
            raise bad("ids out of canonical order (reordered or "
                      "duplicated payload)", i)
        prev = key


def checked_decode(frame_nodes, crc: Optional[int] = None) -> dict:
    """Validate-then-decode one payload: structure first, checksum (if
    the frame carried one) second, ``serde.decode_node_items`` last.
    Raises ``CausalError`` with ``payload-invalid`` / ``payload-
    checksum`` causes instead of letting a poisoned payload reach the
    merge."""
    validate_node_items(frame_nodes)
    if crc is not None and payload_checksum(frame_nodes) != crc:
        raise s.CausalError(
            "sync payload rejected",
            {"causes": {"payload-checksum"},
             "why": "checksum mismatch"},
        )
    try:
        return serde.decode_node_items(frame_nodes)
    except Exception:  # noqa: BLE001 - decode of validated shape
        raise s.CausalError(
            "sync payload rejected",
            {"causes": {"payload-invalid"}, "why": "undecodable"},
        ) from None


def _is_payload_reject(e: s.CausalError) -> bool:
    return bool({"payload-invalid", "payload-checksum"}
                & set(e.info.get("causes", ())))


# ------------------------------------------------ replica quarantine
#
# Repeat offenders: a peer whose payloads keep failing validation is
# either corrupt or hostile; after QUARANTINE_AFTER consecutive
# rejects it is quarantined — delta exchanges skip it (straight to
# the validated full-bag resync) and merge_wave routes its pairs to
# the fully-validating host merge instead of the device kernel. A
# clean full-bag resync re-admits it (``sync.readmit``). The registry
# is process-wide, keyed by the peer replica's site id.

_Q_LOCK = threading.Lock()
_REJECTS: Dict[str, int] = {}   # peer site id -> consecutive rejects
_QUARANTINED: set = set()


def note_reject(peer: str, uuid: str = "", why: str = "") -> int:
    """Record one rejected payload from ``peer``; quarantines it at
    QUARANTINE_AFTER consecutive rejects. Returns the consecutive
    count. Emits ``sync.reject`` (and ``sync.quarantine`` on the
    transition) when obs is on."""
    peer = str(peer or "")
    newly = False
    if peer:
        with _Q_LOCK:
            n = _REJECTS.get(peer, 0) + 1
            _REJECTS[peer] = n
            if n >= QUARANTINE_AFTER and peer not in _QUARANTINED:
                _QUARANTINED.add(peer)
                newly = True
    else:
        n = 1
    if obs.enabled():
        _sem.sync_rejected(why or "payload-invalid", uuid=uuid,
                           peer=peer)
        if newly:
            _sem.sync_quarantined(peer, uuid=uuid, rejects=n)
    return n


def note_clean(peer: str) -> None:
    """A validated payload from ``peer`` landed: the consecutive
    -reject counter resets (quarantine itself only lifts via
    :func:`readmit`). Public since PR 13 — the net server's ingest
    boundary resets offenders exactly like a sync round does (a wire
    corruption is transient; only CONSECUTIVE rejects quarantine)."""
    peer = str(peer or "")
    if not peer:
        return
    with _Q_LOCK:
        _REJECTS.pop(peer, None)


def readmit(peer: str, uuid: str = "") -> bool:
    """Lift ``peer``'s quarantine after a clean validated full-bag
    resync; returns whether it was quarantined. Emits
    ``sync.readmit`` when obs is on. A full bag from a peer that is
    NOT quarantined changes nothing — in particular it does not reset
    the consecutive-reject count, or a repeat offender whose every
    reject heals over a full bag could never cross the threshold."""
    peer = str(peer or "")
    with _Q_LOCK:
        was = peer in _QUARANTINED
        if was:
            _QUARANTINED.discard(peer)
            _REJECTS.pop(peer, None)
    if was and obs.enabled():
        _sem.sync_readmitted(peer, uuid=uuid)
    return was


def is_quarantined(peer) -> bool:
    with _Q_LOCK:
        return str(peer or "") in _QUARANTINED


def any_quarantined() -> bool:
    """Cheap wave-path guard: True iff any replica is quarantined
    (merge_wave checks per-pair only past this)."""
    return bool(_QUARANTINED)


def quarantined() -> frozenset:
    with _Q_LOCK:
        return frozenset(_QUARANTINED)


def quarantine_reset() -> None:
    """Drop all quarantine/offender state (tests)."""
    with _Q_LOCK:
        _REJECTS.clear()
        _QUARANTINED.clear()


def send_frame(stream, obj: dict) -> None:
    payload = json.dumps(obj, allow_nan=False).encode()
    stream.write(_HDR.pack(len(payload)) + payload)
    stream.flush()


def _read_exact(stream, n: int) -> bytes:
    """Accumulate exactly ``n`` bytes. Raw sockets and unbuffered pipes
    may legally return short reads; only an empty read means EOF. A
    stream whose deadline expires (a socket with a timeout set, or the
    net transport's ``FrameStream``) raises the protocol's uniform
    ``read-timeout`` CausalError instead of leaking ``TimeoutError`` —
    the caller treats both as "this peer is dead, degrade/reconnect"."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = stream.read(n - got)
        except TimeoutError:
            # socket.timeout is TimeoutError since 3.10: a silent peer
            # on a deadline-armed stream is a protocol outcome, not a
            # crash — reject uniformly so every caller's except
            # CausalError ladder (full-bag retry, transport reconnect)
            # handles it
            raise s.CausalError(
                "sync read deadline exceeded",
                {"causes": {"read-timeout"}},
            ) from None
        if not chunk:
            raise s.CausalError("sync stream closed mid-frame",
                                {"causes": {"eof"}})
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _arm_deadline(stream, timeout_s: Optional[float]) -> None:
    """Arm a read deadline on a stream that supports one (sockets and
    the net transport's ``FrameStream`` expose ``settimeout``; plain
    buffered file objects don't — for those, set the timeout on the
    underlying socket BEFORE ``makefile()`` and ``_read_exact`` maps
    the raised ``TimeoutError`` to the uniform reject)."""
    if timeout_s is None:
        return
    settimeout = getattr(stream, "settimeout", None)
    if settimeout is not None:
        settimeout(float(timeout_s))


def recv_frame(stream, timeout_s: Optional[float] = None) -> dict:
    _arm_deadline(stream, timeout_s)
    (n,) = _HDR.unpack(_read_exact(stream, _HDR.size))
    if n > MAX_FRAME:
        raise s.CausalError("sync frame too large",
                            {"causes": {"frame-overflow"}, "size": n})
    return json.loads(_read_exact(stream, n))


def exchange_frame(stream, obj: dict,
                   read_timeout_s: Optional[float] = None) -> dict:
    """Send ``obj`` and receive the peer's frame CONCURRENTLY. Both
    sync endpoints are symmetric (each sends, then expects the peer's
    frame of the same kind); writing a large frame before reading
    would deadlock once the two frames exceed the transport buffers,
    so the write happens on a helper thread while this thread reads."""
    err = []

    def _send():
        try:
            send_frame(stream, obj)
        except Exception as e:  # noqa: BLE001 - surfaced below
            err.append(e)

    t = threading.Thread(target=_send, daemon=True)
    t.start()
    try:
        got = recv_frame(stream, timeout_s=read_timeout_s)
        # bounded even on success: a peer that answered and then
        # stopped draining would otherwise hang this join forever. The
        # bound is generous (SEND_DRAIN_TIMEOUT) because a slow uplink
        # legitimately takes minutes for a full-bag frame — only a
        # genuinely wedged peer should trip it.
        t.join(timeout=SEND_DRAIN_TIMEOUT)
        if t.is_alive():
            raise s.CausalError(
                "sync peer stopped draining mid-frame",
                {"causes": {"send-stalled"}},
            )
    except BaseException:
        # The receive failed (bad frame, uuid mismatch, EOF). The
        # writer may be blocked on a transport buffer the peer will
        # never drain; it's a daemon thread, so give it a short grace
        # period and surface the receive error either way.
        t.join(timeout=1.0)
        raise
    if err:
        if isinstance(err[0], TimeoutError):
            # the armed deadline is socket-wide, so a peer that stops
            # DRAINING can time out our send thread too — map it to
            # the same uniform CausalError family the read path uses,
            # or the caller's except-CausalError degrade ladder would
            # miss it and crash on a bare TimeoutError
            raise s.CausalError(
                "sync peer stopped draining mid-frame",
                {"causes": {"send-stalled"}},
            ) from err[0]
        raise err[0]
    return got


def sync_stream(handle, stream, read_timeout_s: Optional[float] = None):
    """One symmetric anti-entropy round over a duplex byte stream (a
    socket ``makefile('rwb')``, a pipe pair, ...). Both ends call this;
    returns the converged handle.

    Round: exchange hello {uuid, type, vv} (uuid and type must match)
    / exchange deltas / merge. If either side flags that a delta was
    inapplicable (non-prefix history, e.g. a weft), fall back to
    exchanging the full bag of nodes. Every exchange is concurrent
    send+recv (``exchange_frame``) so arbitrarily large frames cannot
    deadlock the symmetric protocol.

    ``read_timeout_s`` is the transport's read deadline (PR 13): a
    peer that connects and then goes silent used to wedge the reader
    forever on the first blocking receive — with a deadline armed, the
    round rejects with the uniform ``read-timeout`` CausalError
    instead. The deadline is armed through the stream's ``settimeout``
    when it has one (sockets, the net transport's ``FrameStream``);
    buffered ``makefile()`` streams should arm the timeout on the
    underlying socket instead — either way the raised ``TimeoutError``
    maps to the same reject (tests/test_sync.py pins both spellings).
    """
    ct = handle.ct
    _arm_deadline(stream, read_timeout_s)
    if obs.enabled():
        # wedge-triage heartbeat (PR 10): before the first blocking
        # exchange, so a live monitor can tell "a sync round started
        # and hung mid-protocol" from "no replica is syncing" —
        # the obs watch absence rules read this pairing
        obs.event("run.heartbeat", stage="sync.stream", uuid=ct.uuid)
    hello = exchange_frame(stream, {
        "op": "hello", "uuid": ct.uuid, "type": ct.type,
        # sender identity for the offender/quarantine registry (an
        # old peer without it just gets no quarantine bookkeeping)
        "site": ct.site_id,
        "vv": version_vector(handle),
    })

    def frame_field(frame, op, key):
        # a malformed frame is protocol corruption, not a crash: wrong
        # op, wrong JSON shape, or missing fields all reject uniformly
        if not isinstance(frame, dict) or frame.get("op") != op:
            raise s.CausalError(
                "sync protocol error",
                {"causes": {"bad-frame"}, "expected": op},
            )
        try:
            return frame[key]
        except (KeyError, TypeError):
            raise s.CausalError(
                "sync protocol error",
                {"causes": {"bad-frame"}, "expected": op,
                 "missing": key},
            ) from None

    def nodes_frame(op, nodes_map, mangle_site):
        """An outbound node-carrying frame: canonical encoding, CRC
        computed over the TRUE payload, then the chaos transport
        mangle (after the CRC, exactly where a real link corrupts) —
        so every injected payload fault is detectable."""
        enc = serde.encode_node_items(nodes_map)
        frame = {"op": op, "nodes": enc, "crc": payload_checksum(enc)}
        if _chaos.enabled():
            frame["nodes"] = _chaos.mangle_items(enc, mangle_site)
        return frame

    if (frame_field(hello, "hello", "uuid") != ct.uuid
            or frame_field(hello, "hello", "type") != ct.type):
        raise s.CausalError(
            "Causal UUID missmatch. Merge not allowed.",
            {"causes": {"uuid-missmatch"},
             "uuids": [ct.uuid, hello.get("uuid")]},
        )
    peer_site = hello.get("site")
    peer_site = peer_site if isinstance(peer_site, str) else ""
    peer_vv = frame_field(hello, "hello", "vv")
    if not (isinstance(peer_vv, dict) and all(
            isinstance(site, str)
            and isinstance(h, (list, tuple)) and len(h) == 2
            and all(isinstance(x, int) and not isinstance(x, bool)
                    for x in h)
            for site, h in peer_vv.items())):
        raise s.CausalError(
            "sync protocol error",
            {"causes": {"bad-frame"}, "expected": "hello",
             "missing": "vv"},
        )
    delta = exchange_frame(
        stream,
        nodes_frame("delta", delta_nodes(handle, peer_vv),
                    "sync.delta"),
    )
    ok = True
    reason = None
    if peer_site and is_quarantined(peer_site):
        # quarantined peer: its deltas are not trusted — go straight
        # to the validated full-bag resync, which is also its one
        # road back in (readmission below)
        ok = False
        reason = "quarantined"
        merged = handle
    else:
        try:
            merged = apply_delta(
                handle,
                checked_decode(frame_field(delta, "delta", "nodes"),
                               delta.get("crc")))
            note_clean(peer_site)
        except s.CausalError as e:
            if _is_payload_reject(e):
                # the validate-before-apply boundary: the poisoned
                # payload never reached the merge; the document is
                # untouched and the round heals over the full bag
                ok = False
                reason = "payload-reject"
                merged = handle
                note_reject(peer_site, uuid=ct.uuid,
                            why=next(iter(
                                e.info.get("causes", ("payload",)))))
            elif "cause-must-exist" in e.info.get("causes", ()):
                ok = False
                merged = handle
            else:
                raise
    # prefix-gap / reject fallback: ask for (and offer) the full bag
    peer_state = exchange_frame(stream, {"op": "done" if ok else "resync"})
    if (not isinstance(peer_state, dict)
            or peer_state.get("op") not in ("done", "resync")):
        raise s.CausalError(
            "sync protocol error",
            {"causes": {"bad-frame"}, "expected": "done|resync"},
        )
    if peer_state.get("op") == "resync" or not ok:
        if obs.enabled():
            _sem.sync_full_bag(
                reason or ("cause-must-exist" if not ok
                           else "peer-resync"),
                uuid=ct.uuid)
            _cm.note_full_bag(ct.uuid)
        full = exchange_frame(
            stream, nodes_frame("full", dict(ct.nodes), "sync.full"))
        try:
            merged = apply_delta(
                merged,
                checked_decode(frame_field(full, "full", "nodes"),
                               full.get("crc")),
                _count_as_delta=False)
        except s.CausalError as e:
            if _is_payload_reject(e):
                # a poisoned FULL bag cannot heal this round: reject
                # at the boundary (document untouched) and surface it
                # — the next round retries the resync
                note_reject(peer_site, uuid=ct.uuid,
                            why=next(iter(
                                e.info.get("causes", ("payload",)))))
            raise
        # a clean validated full bag re-admits a quarantined peer —
        # but ONLY on the dedicated resync road (a round that STARTED
        # quarantined): the full bag healing the very round whose
        # rejects caused the quarantine must not instantly undo it,
        # or quarantine would never outlive one protocol round
        if peer_site and reason == "quarantined":
            readmit(peer_site, uuid=ct.uuid)
    return merged


def sync_pair(a, b) -> Tuple[object, object]:
    """In-memory anti-entropy between two handles (the loopback twin of
    ``sync_stream`` — same vv/delta/full-bag-fallback path, no
    framing)."""
    if obs.enabled():
        obs.event("run.heartbeat", stage="sync.pair", uuid=a.ct.uuid)
    va, vb = version_vector(a), version_vector(b)

    def full_bag(dst, src, reason):
        if obs.enabled():
            _sem.sync_full_bag(reason, uuid=dst.ct.uuid)
            _cm.note_full_bag(dst.ct.uuid)
        out = apply_delta(dst, dict(src.ct.nodes),
                          _count_as_delta=False)
        # the in-memory full bag comes straight off the live peer
        # handle (already merge-validated state): it is the
        # quarantine's validated exit ramp — but only on the
        # dedicated resync road (reason "quarantined"), never the
        # same-round heal of the reject that caused the quarantine
        if reason == "quarantined":
            readmit(src.ct.site_id, uuid=dst.ct.uuid)
        return out

    def one_way(dst, src, dst_vv):
        peer = src.ct.site_id
        if is_quarantined(peer):
            return full_bag(dst, src, "quarantined")
        nodes = delta_nodes(src, dst_vv)
        if _chaos.enabled() and nodes:
            # the loopback's transport seam: round-trip the delta
            # through the wire encoding so payload faults (and the
            # validate-before-apply boundary) exercise exactly like a
            # framed stream — chaos-off loopbacks never pay this
            enc = serde.encode_node_items(nodes)
            crc = payload_checksum(enc)
            mangled = _chaos.mangle_items(enc, "sync.delta")
            try:
                nodes = checked_decode(mangled, crc)
                note_clean(peer)
            except s.CausalError as e:
                if not _is_payload_reject(e):
                    raise
                note_reject(peer, uuid=dst.ct.uuid,
                            why=next(iter(
                                e.info.get("causes", ("payload",)))))
                return full_bag(dst, src, "payload-reject")
        try:
            return apply_delta(dst, nodes)
        except s.CausalError as e:
            if "cause-must-exist" not in e.info.get("causes", ()):
                raise
            # non-prefix history (weft, gapped replica): full bag
            return full_bag(dst, src, "cause-must-exist")

    return one_way(a, b, va), one_way(b, a, vb)


def sync_base_pair(a, b) -> Tuple[object, object]:
    """Anti-entropy between two replicas of one CausalBase: sync every
    shared collection pairwise, copy collections the peer lacks, union
    the history logs, and fast-forward the shared clock. Site ids and
    undo/redo cursors stay per-replica (undo inverts only the local
    site's transactions, base/core.cljc:354-369, so remote cursors are
    meaningless here).

    Replicas must fork AFTER the base's root collection exists: two
    sides that each ran their first transaction independently minted
    different root collections, which cannot converge (raised as a
    CausalError, same stance as the uuid merge guard)."""
    ca, cb_ = a.cb, b.cb
    if ca.uuid != cb_.uuid:
        raise s.CausalError(
            "Causal UUID missmatch. Merge not allowed.",
            {"causes": {"uuid-missmatch"}, "uuids": [ca.uuid, cb_.uuid]},
        )
    if (ca.root_uuid and cb_.root_uuid
            and ca.root_uuid != cb_.root_uuid):
        raise s.CausalError(
            "Replicas created their root collections independently.",
            {"causes": {"root-missmatch"},
             "roots": [ca.root_uuid, cb_.root_uuid]},
        )
    root_uuid = ca.root_uuid or cb_.root_uuid

    cols_a = dict(ca.collections)
    cols_b = dict(cb_.collections)
    for uuid in set(cols_a) | set(cols_b):
        ha, hb = cols_a.get(uuid), cols_b.get(uuid)
        if ha is not None and hb is not None:
            ha2, hb2 = sync_pair(ha, hb)
            cols_a[uuid], cols_b[uuid] = ha2, hb2
        elif ha is None:
            cols_a[uuid] = hb
        else:
            cols_b[uuid] = ha

    history = sorted(
        {(tuple(nid), uuid) for nid, uuid in ca.history}
        | {(tuple(nid), uuid) for nid, uuid in cb_.history}
    )
    ts = max(ca.lamport_ts, cb_.lamport_ts)
    base_cls = type(a)
    a2 = base_cls(ca.evolve(collections=cols_a, history=list(history),
                            lamport_ts=ts, root_uuid=root_uuid))
    b2 = base_cls(cb_.evolve(collections=cols_b, history=list(history),
                             lamport_ts=ts, root_uuid=root_uuid))
    return a2, b2
