"""Multi-chip execution: shard the replica axis of batched weaves/merges
over a ``jax.sharding.Mesh``.

The reference's "distributed systems layer" is the CRDT itself — any
transport that moves immutable nodes between sites converges
(reference: README.md:5). cause_tpu keeps that host-level story (nodes
are plain data; serde ships them anywhere) and adds the device-level
story the reference never had: a batch of replica merges is sharded
across chips over ICI/DCN with ``shard_map``, with XLA collectives
(psum) reducing fleet-wide convergence stats — no NCCL/MPI port, just
shardings on one jitted program.

Batched merges are embarrassingly parallel across replicas, so the
sharding is pure data parallelism on the batch axis; the collectives
carry only the small cross-replica reductions (visible-node totals,
conflict flags, digest agreement) that a control plane wants after a
merge wave.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..switches import raw_switch_key
from ..weaver.jaxw import merge_weave_kernel, merge_weave_kernel_v2

try:  # JAX >= 0.4.35 exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = [
    "REPLICA_AXIS",
    "make_mesh",
    "mix32",
    "mix32_np",
    "replica_digest",
    "sharded_merge_weave",
    "sharded_merge_weave_v4",
    "sharded_merge_weave_v5",
]

REPLICA_AXIS = "replicas"


def make_mesh(n_devices: Optional[int] = None, axis: str = REPLICA_AXIS) -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices (all by
    default). The replica batch axis of every batched kernel shards
    over this axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def replica_digest(hi_sorted, lo_sorted, rank, visible):
    """An order-sensitive digest of one replica's weave: replicas that
    converged to the same linearization get the same digest, whatever
    lane order their inputs arrived in (node identity and weave
    position are mixed, lane positions are not). Cheap stand-in for
    shipping whole weaves around when checking fleet convergence.

    Each lane goes through a murmur3-style avalanche before the
    permutation-invariant sum: a plain xor-of-products mix let rows
    whose lanes differ only in site ranks cancel into collisions
    (observed in the wild at 4 rows).

    SCOPE: comparable only within one interner domain (one process /
    one fleet session) — hi/lo encode interner-assigned site RANKS,
    which are first-seen-order per process. Convergence checks ACROSS
    hosts use the canonical, rank-free ``cause_tpu.content_digest``
    instead (the two-process distributed test does)."""
    m = rank.shape[0]
    kept = rank < m
    pos = jnp.where(kept, rank.astype(jnp.uint32), jnp.uint32(0))
    x = mix32(hi_sorted, lo_sorted, pos, visible)
    return jnp.sum(jnp.where(kept, x, jnp.uint32(0)))


def mix32_np(hi, lo, pos, visible):
    """Numpy twin of ``mix32``'s per-lane avalanche term — returns the
    uint32 term array (callers sum the kept lanes). The delta-native
    weave uses it to freeze a resident prefix's digest contribution
    host-side, so the arithmetic here MUST stay bit-identical to
    ``mix32`` below; tests/test_delta_weave.py pins the pair against
    each other and against ``replica_digest`` end to end."""
    x = (
        hi.astype(np.uint32) * np.uint32(0x9E3779B1)
        + lo.astype(np.uint32) * np.uint32(0x85EBCA77)
        + pos.astype(np.uint32) * np.uint32(0xC2B2AE35)
        + visible.astype(np.uint32) * np.uint32(40503)
        + np.uint32(1)
    )
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> np.uint32(16))
    return x


def mix32(hi, lo, pos, visible):
    """The per-lane murmur3-style avalanche term of the convergence
    digest — the ONE traced copy: ``replica_digest`` sums it over a
    replica's kept lanes, and the delta wave
    (``weaver.jaxwd.batched_delta_weave``) sums it over window lanes
    at offset positions. ``mix32_np`` above is its numpy twin."""
    x = (
        hi.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
        + lo.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
        + pos.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
        + visible.astype(jnp.uint32) * jnp.uint32(40503)
        + jnp.uint32(1)
    )
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _fleet_reductions(axis, hi, lo, rank, visible, conflict, overflow):
    """The psum-reduced fleet stats + per-replica digests every kernel
    variant reports. ``hi``/``lo`` may arrive in any per-replica lane
    order matching ``rank``'s coordinates — the digest mix-sum is
    permutation-invariant."""
    n_overflow = lax.psum(jnp.sum(overflow.astype(jnp.int32)), axis)
    digest = jax.vmap(replica_digest)(hi, lo, rank, visible)
    total_visible = lax.psum(jnp.sum(visible.astype(jnp.int32)), axis)
    n_conflicts = lax.psum(jnp.sum(conflict.astype(jnp.int32)), axis)
    return digest, total_visible, n_conflicts, n_overflow


def _fleet_stats(axis, hi, lo, order, rank, visible, conflict, overflow):
    """Sorted-lane epilogue: resort the id lanes by ``order`` (rank is
    per sorted lane for v1-v4) and attach the shared reductions."""
    hi_sorted = jnp.take_along_axis(hi, order, axis=1)
    lo_sorted = jnp.take_along_axis(lo, order, axis=1)
    digest, total_visible, n_conflicts, n_overflow = _fleet_reductions(
        axis, hi_sorted, lo_sorted, rank, visible, conflict, overflow
    )
    return (order, rank, visible, digest, total_visible, n_conflicts,
            n_overflow)


@lru_cache(maxsize=8)
def _sharded_step(mesh: Mesh, k_max: int, kernel: str,
                  switches: tuple):
    """The jitted sharded merge step for one mesh (cached so repeat
    merge waves hit the jit cache instead of re-tracing). ``k_max`` > 0
    runs a compressed kernel — ``kernel`` picks the sparse-irregular
    "v3" (default) or chain-compressed "v2" — with that run budget
    (overflowed rows are psum-counted fleet-wide); 0 runs the
    uncompressed kernel.

    ``switches`` is the ``raw_switch_key()`` snapshot and exists ONLY
    to key the cache: the kernels read the CAUSE_TPU_* strategy
    switches via ``resolve()`` at trace time, so a cache keyed on
    (mesh, k_max, kernel) alone kept serving the step traced under the
    PREVIOUS switch config after a flip — the same stale-program class
    benchgen.merge_wave_scalar's key fixed in round 4. A distinct
    snapshot mints a fresh ``jax.jit`` wrapper, whose own aval cache
    then re-traces under the new config."""
    axis = mesh.axis_names[0]
    sharded = P(axis)
    replicated = P()
    if kernel == "v3":
        from ..weaver.jaxw3 import merge_weave_kernel_v3 as _compressed
    else:
        _compressed = merge_weave_kernel_v2

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(sharded,) * 6,
        out_specs=(sharded, sharded, sharded, sharded, replicated,
                   replicated, replicated),
    )
    def step(hi, lo, chi, clo, vc, va):
        if k_max > 0:
            order, rank, visible, conflict, overflow = jax.vmap(
                lambda *r: _compressed(*r, k_max)
            )(hi, lo, chi, clo, vc, va)
        else:
            order, rank, visible, conflict = jax.vmap(merge_weave_kernel)(
                hi, lo, chi, clo, vc, va
            )
            overflow = jnp.zeros(conflict.shape, bool)
        return _fleet_stats(axis, hi, lo, order, rank, visible, conflict,
                            overflow)

    return jax.jit(step)


def sharded_merge_weave(mesh: Mesh, hi, lo, cause_hi, cause_lo, vclass, valid,
                        k_max: int = 0, kernel: str = "v3"):
    """Run the batched merge+weave with the replica axis sharded over
    the mesh. Returns per-replica ``(order, rank, visible, digest)``
    (sharded) plus fleet-level ``(total_visible, n_conflicts,
    n_overflow)`` reduced with psum over the mesh axis. ``k_max`` > 0
    selects a compressed kernel (``kernel``: "v3" sparse-irregular,
    the default, or "v2" chain-compressed) with that per-replica run
    budget; rows counted in ``n_overflow`` carry invalid ranks and the
    caller should rerun with ``k_max=0`` (or a bigger budget).

    The batch dimension must be divisible by the mesh size.
    """
    # normalize the cache key: kernel is only consulted when k_max > 0,
    # so k_max=0 calls must not mint per-kernel duplicate programs
    step = _sharded_step(mesh, k_max, kernel if k_max > 0 else "v1",
                         raw_switch_key())
    return step(hi, lo, cause_hi, cause_lo, vclass, valid)


@lru_cache(maxsize=8)
def _sharded_step_v4(mesh: Mesh, k_max: int, switches: tuple):
    """The v4 twin of ``_sharded_step``: 5 lanes (cause ids replaced by
    the marshal-time concat cause-index lane), same outputs.
    ``switches`` keys the cache on the trace-time strategy snapshot
    (see ``_sharded_step``)."""
    from ..weaver.jaxw4 import merge_weave_kernel_v4

    axis = mesh.axis_names[0]
    sharded = P(axis)
    replicated = P()

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(sharded,) * 5,
        out_specs=(sharded, sharded, sharded, sharded, replicated,
                   replicated, replicated),
    )
    def step(hi, lo, cci, vc, va):
        order, rank, visible, conflict, overflow = jax.vmap(
            lambda *r: merge_weave_kernel_v4(*r, k_max)
        )(hi, lo, cci, vc, va)
        return _fleet_stats(axis, hi, lo, order, rank, visible, conflict,
                            overflow)

    return jax.jit(step)


def sharded_merge_weave_v4(mesh: Mesh, hi, lo, cci, vclass, valid,
                           k_max: int):
    """``sharded_merge_weave`` for the v4 kernel: lanes carry ``cci``
    (the cause's index in the concatenated pre-sort array, resolved at
    marshal time) instead of cause id lanes. Same outputs; the batch
    dimension must be divisible by the mesh size."""
    return _sharded_step_v4(mesh, k_max, raw_switch_key())(
        hi, lo, cci, vclass, valid)


@lru_cache(maxsize=8)
def _sharded_step_v5(mesh: Mesh, u_max: int, k_max: int,
                     pipeline: str, switches: tuple):
    """The v5 (segment-union) sharded step: node lanes + segment
    tables in, per-replica (rank, visible, digest) + fleet stats out;
    ``switches`` keys the cache on the trace-time strategy snapshot
    (see ``_sharded_step``).
    v5 reports in concat-lane coordinates and produces no ``order``;
    the digest's mix-sum is permutation-invariant, so feeding the raw
    lanes with concat-coordinate ranks yields the same digest value as
    the sorted-lane kernels. ``pipeline`` picks the row kernel: "v5"
    (jaxw5) or "v5f" (the fused token pipeline, jaxw5f)."""
    if pipeline == "v5f":
        from ..weaver.jaxw5f import (
            merge_weave_kernel_v5f as _row_kernel)

        def merge_weave_kernel_v5(*r, u_max, k_max):
            return _row_kernel(*r, u_max=u_max, k_max=k_max)
    else:
        from ..weaver.jaxw5 import merge_weave_kernel_v5

    axis = mesh.axis_names[0]
    sharded = P(axis)
    replicated = P()
    # pallas_call inside shard_map cannot express varying-mesh-axes
    # metadata on its outputs; the fused pipeline disables the vma
    # check (outputs are per-row, trivially sharded like the inputs)
    extra = {"check_vma": False} if pipeline == "v5f" else {}

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(sharded,) * 16,
        out_specs=(sharded, sharded, sharded, sharded, replicated,
                   replicated, replicated),
        **extra,
    )
    def step(hi, lo, cci, vc, va, seg, *sg):
        rank, visible, conflict, overflow = jax.vmap(
            lambda *r: merge_weave_kernel_v5(*r, u_max=u_max, k_max=k_max)
        )(hi, lo, cci, vc, va, seg, *sg)
        digest, total_visible, n_conflicts, n_overflow = _fleet_reductions(
            axis, hi, lo, rank, visible, conflict, overflow
        )
        # per-row overflow rides out sharded: overflowed v5 rows keep
        # many plausible-looking ranks, so callers cannot reconstruct
        # the flags from rank alone
        return (rank, visible, overflow, digest, total_visible,
                n_conflicts, n_overflow)

    return jax.jit(step)


def sharded_merge_weave_v5(mesh: Mesh, lanes: dict, u_max: int,
                           k_max: int, pipeline: str = "v5"):
    """Shard the v5 segment-union merge over the mesh. ``lanes`` is the
    ``benchgen.LANE_KEYS5`` dict of [B, ...] arrays. Returns
    ``(rank, visible, overflow, digest, total_visible, n_conflicts,
    n_overflow)`` — rank/visible/overflow per replica row (no order
    array in the v5 contract; ``overflow`` rows carry garbage ranks
    and must be re-run).

    CAVEAT (narrowed in round 3 by the sg_vsum checksum lane): twin
    dedupe now verifies member value CLASSES and structure, so
    class-divergent corrupt twins explode and count in
    ``n_conflicts``; what remains device-invisible is host VALUE bytes
    (identical ids/classes/causes, different payload). Fleet control
    planes that must catch those validate bodies host-side
    (shared.union_nodes does)."""
    from ..benchgen import LANE_KEYS5

    step = _sharded_step_v5(mesh, u_max, k_max, pipeline,
                            raw_switch_key())
    return step(*(lanes[k] for k in LANE_KEYS5))
