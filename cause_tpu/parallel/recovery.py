"""The explicit recovery ladder: one declared degradation policy for
every device dispatch seam.

Before PR 11 the substrate's degradation story was real but IMPLICIT —
scattered, un-evidenced fallbacks: the session silently bounced
delta -> full on a domain violation, merge_wave silently doubled its
token budget on overflow and silently host-merged rows that still
overflowed, the tree silently bounced a level to full width. Correct,
but invisible: an operator watching the obs stream could not tell a
healthy fleet from one quietly degrading to O(doc) every wave, and a
transient device failure (one flaky dispatch) killed the whole wave
instead of being retried.

This module reifies that policy as ONE named ladder shared by the
session, tree and merge_wave dispatch sites:

    delta -> full -> double_budget -> host

- :func:`step` is the evidence: every rung transition emits one
  ``recovery.step`` event (site, from/to rung, reason) plus counters,
  so the fleet CLI / live monitor can rate-alert on recovery storms —
  obs-off it is a no-op (call sites keep the obs-guard idiom);
- :func:`run_dispatch` is the execution seam: it runs one device
  dispatch with the chaos engine's injected faults applied and
  bounded retry + linear backoff on TRANSIENT failures (chaos'
  ``InjectedDispatchError``, runtime-classified XLA transport errors)
  — a flaky dispatch costs a retry, not the wave, while a failure
  that survives every retry propagates loudly (with
  ``recovery.exhausted`` evidence) rather than silently degrading.
  Healthy-path cost is one ``chaos.enabled()`` read and a try frame
  (measured <1% of wave wall, PERF.md "Round 11").

The ladder is POLICY, not mechanism: the rungs' implementations stay
where they always lived (session/_full_wave, wave.dispatch_full_rows'
doubled budget, merge_wave's host fallback); this module names the
transitions and makes every one observable.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

from .. import chaos as _chaos
from .. import obs

__all__ = [
    "LADDER",
    "MAX_RETRIES",
    "BACKOFF_S",
    "step",
    "is_transient",
    "run_dispatch",
    "restore_recorded",
]

# the rungs, in degradation order; "host" is the pure-weaver host
# fallback — always correct, never fast
LADDER: Tuple[str, ...] = ("delta", "full", "double_budget", "host")

# bounded retry for transient device failures: a real device flake is
# either gone on the second try or it is not transient
MAX_RETRIES = 2
BACKOFF_S = 0.02

# exception type NAMES classified as transient device failures —
# jaxlib types cannot be imported here (obs-layer modules stay
# importable without jax), and an isinstance against the chaos error
# covers the injected family
_TRANSIENT_NAMES = frozenset({"XlaRuntimeError"})


def step(site: str, from_step: str, to_step: str, reason: str,
         uuid: str = "", **extra) -> None:
    """Record one ladder transition (``recovery.step`` event +
    per-rung counter). No-op with obs off — call sites keep the
    obs-guard idiom so causelint CHS001 can gate jit-reachable
    paths."""
    if not obs.enabled():
        return
    obs.counter("recovery.steps").inc()
    obs.counter(f"recovery.step.{to_step}").inc()
    fields = {"site": site, "from": from_step, "to": to_step,
              "reason": reason}
    if uuid:
        fields["uuid"] = uuid
    if extra:
        fields.update(extra)
    obs.event("recovery.step", **fields)


def is_transient(exc: BaseException) -> bool:
    """Whether a dispatch failure is worth retrying: the chaos
    engine's injected transient, or a runtime-classified XLA
    transport error. Everything else (shape errors, CausalError,
    OOM) propagates immediately — retrying a deterministic failure
    just burns the backoff."""
    if isinstance(exc, _chaos.InjectedDispatchError):
        return True
    return type(exc).__name__ in _TRANSIENT_NAMES


def run_dispatch(site: str, fn: Callable, *,
                 retries: int = MAX_RETRIES,
                 backoff_s: float = BACKOFF_S,
                 uuid: str = ""):
    """Execute one device dispatch through the ladder's retry rung:
    chaos dispatch faults are injected here (so every dispatch seam
    is injectable by construction), transient failures retry up to
    ``retries`` times with linear backoff (``recovery.retry``
    events), and exhaustion emits ``recovery.exhausted`` before
    re-raising. A failure that survives every retry is NOT absorbed:
    it propagates and the wave fails loudly with the ``recovery.
    exhausted`` evidence in the stream — a device that fails the
    same dispatch three times is not transient, and silently
    degrading to the host rung on an unclassified error would mask
    real defects (the ladder's other rungs handle the *declared*
    degradations: domain violations, budget overflows, quarantine).

    Sanctioned unguarded (causelint CHS001 skips it): this IS the
    dispatch path, and its idle cost is one ``chaos.enabled()`` read
    plus a try frame."""
    attempt = 0
    while True:
        try:
            if _chaos.enabled():
                _chaos.dispatch_fault(site)
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if not is_transient(e):
                raise
            if attempt >= retries:
                if obs.enabled():
                    obs.counter("recovery.exhausted").inc()
                    obs.event("recovery.exhausted", site=site,
                              attempts=attempt + 1,
                              error=type(e).__name__,
                              **({"uuid": uuid} if uuid else {}))
                raise
            attempt += 1
            if obs.enabled():
                obs.counter("recovery.retry").inc()
                obs.event("recovery.retry", site=site, attempt=attempt,
                          error=type(e).__name__,
                          **({"uuid": uuid} if uuid else {}))
            if backoff_s:
                time.sleep(backoff_s * attempt)


def restore_recorded(site: str, pairs: int, delta_restored: bool,
                     uuid: str = "") -> None:
    """Evidence of a checkpoint restore (``recovery.restore``): a
    crashed process came back and resumed — with its delta frontier
    when ``delta_restored`` (the steady-state resume the checkpoint
    exists for), without it when the frontier failed revalidation
    (the next wave re-establishes at full width)."""
    if not obs.enabled():
        return
    obs.counter("recovery.restores").inc()
    obs.event("recovery.restore", site=site, pairs=int(pairs),
              delta_restored=bool(delta_restored),
              **({"uuid": uuid} if uuid else {}))
