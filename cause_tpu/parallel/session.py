"""Device-resident fleet sessions: merge waves without re-shipping the
fleet.

``merge_wave`` assembles and uploads the full [B, 2*cap] lane batch on
every call — fine on-package, but the axon-tunneled TPU pays the full
host->device transfer (hundreds of MB per wave at north-star scale)
every time. A ``FleetSession`` keeps the batch ON DEVICE between waves
and ships only what changed:

- per edited tree, the appended delta lanes (the lane cache knows the
  previous wave's length; appends are the steady state) — a
  [B, 2, d_max] upload of a few KB;
- the per-row segment tables (always small: tens of entries per row),
  re-sent wholesale each wave;
- a jitted scatter program splices the deltas into the resident lanes
  (per-row dynamic offsets via masked index scatter — static shapes,
  no recompiles while d_max stays inside the session's budget).

A tree whose cache dropped (mid-order insert, weft) or whose delta
exceeds the budget falls back to a full re-upload of the whole batch
that wave — correct, just slower. ``wave()`` then converges the fleet
and fetches ONE small digest array; ranks and visibility stay
device-resident for on-demand materialization.

**Delta-native waves (PR 7).** Residency alone still paid a full
-document-width KERNEL per wave. After any full-width wave the session
freezes a per-pair *delta frontier* — the shared converged lane
prefix, its weave-final node (the anchor every divergent subtree
attaches under), and the prefix's exact uint32 digest contribution —
and steady-state waves dispatch ``weaver.jaxwd.batched_delta_weave``
over just the divergent WINDOW (anchor + suffix lanes), splicing
ranks/visibility back into the resident weave and returning digests
bit-identical to the full wave's. Device work per wave is then
O(divergence); first contact, domain violations
(``wave.delta_domain_ok``), window-budget overflow, and every
update-level fallback run the full kernel and re-establish.

This is the TPU-native sync-fleet loop: edit replicas on host, ship
deltas, weave ONLY the deltas on device, read digests.

**Fleet convergence (PR 8).** Waves converge PAIRS; bringing the whole
resident fleet to one state is ``converge()``, which routes through
the merge reduction tree (``parallel.tree``): ceil(log2(n)) batched
device rounds instead of the n-1 sequential pairwise waves of the
flat fold (retained behind ``converge(tree=False)`` as the A/B
control).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import chaos as _chaos
from .. import obs
from ..collections import shared as s
from ..weaver import lanecache
from ..weaver.arrays import next_pow2
from ..weaver.segments import SEG_LANE_KEYS, concat_seg_tables
from . import recovery as _recovery
from .wave import (WaveBuffers, _PAD, _assemble_rows, _digest_fn,
                   _observe_semantics, _sampled_body_spotcheck,
                   assemble_delta_window, delta_domain_ok)

__all__ = ["FleetSession"]

_LANE_COLS = ("hi", "lo", "cci", "vc", "valid", "seg")


@partial(jax.jit, donate_argnums=(0,))
def _apply_deltas(dev: Dict[str, jnp.ndarray], deltas: Dict[str, jnp.ndarray],
                  starts, counts, b_shift, old_nb):
    """Splice per-tree delta lanes into the resident batch.

    ``deltas[col]`` is [B, 2, d_max]; ``starts``/``counts`` [B, 2] are
    each tree's previous length and delta size (concat-lane start =
    tree_offset + start). ``b_shift`` [B] re-bases tree B's OLD seg
    ordinals when tree A gained segments; ``old_nb`` [B] bounds that
    shift to B's pre-delta lanes. Buffer-donated: the resident arrays
    update in place on device.
    """
    B, N = dev["hi"].shape
    cap = N // 2
    d_max = deltas["hi"].shape[2]
    off = jnp.arange(d_max, dtype=jnp.int32)

    lane_idx = jnp.arange(N, dtype=jnp.int32)
    shift_mask = (
        (lane_idx[None, :] >= cap)
        & (lane_idx[None, :] < cap + old_nb[:, None])
        & (dev["seg"] >= 0)
    )
    out = dict(dev)
    out["seg"] = jnp.where(shift_mask, dev["seg"] + b_shift[:, None],
                           dev["seg"])

    def one_col(col, arr):
        def row(row_arr, st, ct, d):
            # masked index scatter: lanes beyond the count drop
            for t in range(2):
                idx = t * cap + st[t] + off
                idx = jnp.where(off < ct[t], idx, N)
                row_arr = row_arr.at[idx].set(d[t], mode="drop")
            return row_arr

        return jax.vmap(row)(arr, starts, counts, deltas[col])

    for col in _LANE_COLS:
        out[col] = one_col(col, out[col])
    return out


class FleetSession:
    """A device-resident batch of replica pairs converged wave after
    wave. See the module docstring; usage::

        sess = FleetSession(pairs)          # full upload once
        d0 = sess.wave()                    # digests, device-resident
        pairs = edit(pairs)                 # host-side appends
        sess.update(pairs)                  # ship deltas only
        d1 = sess.wave()
    """

    def __init__(self, pairs: Sequence[Tuple[object, object]],
                 d_max: int = 256, u_headroom: float = 2.0,
                 delta: bool = True):
        pairs = list(pairs)
        if not pairs:
            raise s.CausalError("Nothing to merge.",
                                {"causes": {"empty-fleet"}})
        for a, b in pairs:
            s.check_mergeable(a.ct, b.ct)
        # map trees (rejected by view_for — they need the mapw forest
        # encoding) and off-domain ids surface as the outside-domain
        # raise from the first _full_upload
        self.d_max = int(d_max)
        self._bufs = WaveBuffers()
        self._views: List[Tuple[object, object]] = []
        self._uploaded_n = None     # [B, 2] lane counts on device
        self._uploaded_k = None     # [B] tree-A segment counts on device
        self.capacity = 0
        self.u_max = 0
        self._u_headroom = float(u_headroom)
        self.dev = None
        # wave cost-model bookkeeping: what the LAST update shipped
        # (delta lanes vs a full O(doc) re-upload) — the next wave()'s
        # wave.cost event carries it as divergence evidence
        self._last_delta_lanes = 0
        self._last_update_full = False
        # delta-native wave state, established after each full wave
        # (see _establish_delta): None = next wave runs full width.
        # ``delta=False`` pins the session to full-width waves (the
        # A/B control and the escape hatch). Establishment costs an
        # O(doc) rank fetch, so repeated failures (a fleet whose edits
        # keep violating the delta domain) back off permanently after
        # _DELTA_FAILURE_LIMIT consecutive misses.
        self._delta_enabled = bool(delta)
        self._delta = None
        self._delta_failures = 0
        # the last wave's fetched digests: checkpoint() serializes
        # them and restore() gates on recomputing them bit-identically
        self._last_digest = None
        self._full_upload(pairs)

    _DELTA_FAILURE_LIMIT = 3

    # Batched-serving state (see window_pack/complete_window): the
    # last bucket dispatch's unspliced window output, the deferred
    # device-lane mode flag, and whether the resident lanes are behind
    # the host views. Class-level defaults so restore()d and
    # pre-existing pickled sessions get the unbatched behavior.
    _pending_window = None
    _dev_stale = False
    defer_device = False

    # ------------------------------------------------------------------
    def _collect_views(self, pairs):
        views = []
        for a, b in pairs:
            va = lanecache.view_for(a.ct)
            vb = lanecache.view_for(b.ct)
            if va is None or vb is None or not lanecache.compatible(
                    (va, vb)):
                return None
            views.append((va, vb))
        return views

    def _full_upload(self, pairs):
        with obs.span("session.full_upload", pairs=len(pairs)):
            obs.counter("session.full_upload").inc()
            return self._full_upload_inner(pairs)

    def _full_upload_inner(self, pairs):
        views = self._collect_views(pairs)
        if views is None:
            raise s.CausalError(
                "fleet outside the device domain (PackSpec overflow?)",
                {"causes": {"outside-domain"}},
            )
        cap = next_pow2(max(max(va.n, vb.n) for va, vb in views))
        if cap < self.capacity:
            cap = self.capacity  # never shrink: resident shapes are fixed
        # device-resident rounds never see host value bytes: sampled
        # append-only body check on every (re-)upload (see wave.py)
        _bad = _sampled_body_spotcheck(views)
        if _bad:
            raise next(iter(_bad.values()))
        lanes = _assemble_rows(views, cap, bufs=self._bufs)
        from ..benchgen import v5_token_budget

        u = v5_token_budget(lanes)
        # pow2-quantized (stable XLA program shapes across sessions
        # and re-uploads)
        self.u_max = max(self.u_max, next_pow2(
            int(u * self._u_headroom) + self.d_max
        ))
        if obs.enabled():
            # resident-budget headroom: how far the CURRENT fleet sits
            # below the session's compiled token ceiling
            from ..obs import semantic as _sem

            _sem.token_headroom(int(self.u_max) - int(u), "session")
        self.capacity = cap
        self.dev = {k: jnp.asarray(v) for k, v in lanes.items()}
        self._views = views
        self._uploaded_n = np.array(
            [[va.n, vb.n] for va, vb in views], np.int32
        )
        self._uploaded_k = np.array(
            [int(va.segments()["sg_len"].shape[0]) for va, _ in views],
            np.int32,
        )
        # what the delta path must verify survived unchanged: the
        # per-lane segment ordinals of every uploaded prefix (an
        # interior stab restructures them) and the interner rank
        # generation (a reassignment repacks every lo)
        self._uploaded_rol = [
            (va.segments()["run_of_lane"], vb.segments()["run_of_lane"])
            for va, vb in views
        ]
        self._gen = views[0][0].interner.generation
        self.pairs = list(pairs)
        # a full upload is the session's O(doc) degradation: the next
        # wave.cost records it as a full-bag wave with zero delta ops,
        # and the delta-wave capability drops until the next full wave
        # re-establishes the resident frontier
        self._last_delta_lanes = 0
        self._last_update_full = True
        self._delta = None
        self._dev_stale = False
        self._pending_window = None
        if obs.enabled():
            from ..obs import devprof

            devprof.sample_device_memory("session.upload")

    # ------------------------------------------------------------------
    def _degrade(self, pairs, reason: str):
        """The update-level ``delta -> full`` recovery-ladder rung:
        every full re-upload taken from the delta path is a declared,
        evidenced transition (``recovery.step``), not a silent
        bounce."""
        if obs.enabled():
            _recovery.step(
                "session", "delta", "full", reason,
                uuid=str(pairs[0][0].ct.uuid) if pairs else "")
        return self._full_upload(pairs)

    def update(self, pairs: Sequence[Tuple[object, object]]):
        """Ship this wave's edits. Appends ride the delta path; anything
        else (dropped caches, oversized deltas, capacity growth) falls
        back to a full re-upload."""
        pairs = list(pairs)
        # an update invalidates the checkpointable state until the
        # next wave: the resident pairs (and possibly capacity) move
        # ahead of the last wave's rank/visibility/digest arrays, and
        # a checkpoint mixing the two could never pass restore's
        # digest gate
        self._last_digest = None
        with obs.span("session.update", pairs=len(pairs)):
            return self._update_inner(pairs)

    def _update_inner(self, pairs):
        if len(pairs) != len(self._views):
            return self._degrade(pairs, "pair-count-change")
        views = self._collect_views(pairs)
        if views is None:
            raise s.CausalError(
                "fleet outside the device domain",
                {"causes": {"outside-domain"}},
            )
        if views[0][0].interner.generation != self._gen:
            # rank reassignment since upload: resident lo/sg packs are
            # old-generation, deltas would be new-generation
            return self._degrade(pairs, "rank-reassignment")
        B = len(pairs)
        cap = self.capacity
        d_max = self.d_max
        starts = np.zeros((B, 2), np.int32)
        counts = np.zeros((B, 2), np.int32)
        tables = {k: [] for k in SEG_LANE_KEYS}
        b_shift = np.zeros(B, np.int32)
        old_nb = np.zeros(B, np.int32)
        s_needed = 0
        for r, ((va, vb), (ova, ovb)) in enumerate(
                zip(views, self._views)):
            for t, (v, ov) in enumerate(((va, ova), (vb, ovb))):
                n0 = int(self._uploaded_n[r, t])
                if (v.arena is not ov.arena and ov.arena.nodes[:n0]
                        != v.arena.nodes[:n0]):
                    return self._degrade(pairs, "rewritten-history")
                if v.n < n0 or v.n - n0 > d_max or v.n > cap:
                    return self._degrade(pairs, "delta-overflow")
                # an append that stabbed an old interior lane
                # restructures the uploaded prefix's segment ordinals —
                # the resident seg lane would be silently stale
                if not np.array_equal(
                        v.segments()["run_of_lane"][:n0],
                        self._uploaded_rol[r][t][:n0]):
                    return self._degrade(pairs, "interior-stab")
            segs_a, segs_b = va.segments(), vb.segments()
            ka = int(segs_a["sg_len"].shape[0])
            kb = int(segs_b["sg_len"].shape[0])
            s_needed = max(s_needed, ka + kb)
        s_max = self.dev["sg_len"].shape[1]
        if s_needed > s_max:
            return self._degrade(pairs, "segment-overflow")

        # delta path committed from here on. The sampled append-only
        # body check runs once per round: here on the delta path, or
        # inside _full_upload when a branch above delegated to it (the
        # corrupt lane may be resident from a previous upload, so the
        # check always covers whole trees, not just deltas).
        _bad = _sampled_body_spotcheck(views)
        if _bad:
            raise next(iter(_bad.values()))
        obs.counter("session.delta_update").inc()

        if self._delta is not None:
            # delta-WAVE domain (stricter than the lane-splice domain
            # above): every appended lane must weave strictly after the
            # frozen resident prefix — causes inside the divergent
            # window or on the anchor, no tombstone of the anchor, and
            # the window must fit the session's compiled budget. A
            # violation only drops the delta-wave capability (the next
            # wave runs full width and re-establishes); the resident
            # lane splice stays valid either way.
            dstate = self._delta
            w_cap = dstate["w_cap"]
            for r, (va, vb) in enumerate(views):
                sp = int(dstate["s"][r])
                anchor = int(dstate["anchor"][r])
                ok = True
                for t, v in enumerate((va, vb)):
                    if v.n - sp > w_cap - 1:
                        ok = False  # window outgrew the budget
                        break
                    if not delta_domain_ok(
                            v, sp, anchor,
                            start=int(self._uploaded_n[r, t])):
                        ok = False
                        break
                if not ok:
                    obs.counter("session.delta_wave_invalidate").inc()
                    if obs.enabled():
                        # the splice stays valid; only the delta-WAVE
                        # capability drops — the next wave runs the
                        # full rung and re-establishes
                        _recovery.step(
                            "session", "delta", "full",
                            "domain-violation",
                            uuid=str(pairs[0][0].ct.uuid), pair=r)
                    self._delta = None
                    break

        # Batched serving defers the resident lane splice: with a live
        # frontier the delta wave assembles its window from host views
        # only, so the device lanes can stay behind until the next
        # full-width wave (which re-uploads). Without a live frontier
        # the next wave is full-width and needs current lanes — stale
        # residents take the declared full-upload rung instead of a
        # splice onto lanes that no longer match the bookkeeping.
        defer = self.defer_device and self._delta is not None
        if not defer and self._dev_stale:
            return self._degrade(pairs, "stale-resident-lanes")
        deltas = None
        if not defer:
            deltas = {c: np.full((B, 2, d_max), _PAD[c],
                                 self.dev[c].dtype
                                 if c != "valid" else bool)
                      for c in _LANE_COLS}

        for r, ((va, vb), _old) in enumerate(zip(views, self._views)):
            segs_a, segs_b = va.segments(), vb.segments()
            ka = int(segs_a["sg_len"].shape[0])
            old_ka = int(self._uploaded_k[r])
            b_shift[r] = ka - old_ka
            old_nb[r] = int(self._uploaded_n[r, 1])
            for t, (v, segs) in enumerate(((va, segs_a), (vb, segs_b))):
                a = v.arena
                n0 = int(self._uploaded_n[r, t])
                d = v.n - n0
                starts[r, t] = n0
                counts[r, t] = d
                if d and not defer:
                    sl = slice(n0, v.n)
                    deltas["hi"][r, t, :d] = a.ts[sl]
                    deltas["lo"][r, t, :d] = a.spec.pack_lo(
                        a.site[sl], a.tx[sl]
                    )
                    ci = a.cause_idx[sl]
                    deltas["cci"][r, t, :d] = np.where(
                        ci >= 0, ci + t * cap, -1
                    )
                    deltas["vc"][r, t, :d] = a.vclass[sl]
                    deltas["valid"][r, t, :d] = True
                    base = 0 if t == 0 else ka
                    deltas["seg"][r, t, :d] = (
                        segs["run_of_lane"][n0:v.n] + base
                    )
                self._uploaded_n[r, t] = v.n
            self._uploaded_k[r] = ka
            self._uploaded_rol[r] = (
                segs_a["run_of_lane"], segs_b["run_of_lane"]
            )
            if not defer:
                # small per-row tables, rebuilt host-side every wave
                # via the shared layout helper
                row, _bases = concat_seg_tables(
                    [(segs_a, int(self._uploaded_n[r, 0])),
                     (segs_b, int(self._uploaded_n[r, 1]))],
                    cap, s_max,
                )
                for k in SEG_LANE_KEYS:
                    tables[k].append(row[k])

        if defer:
            # batched serving: the delta wave assembles its window
            # from host views, so the resident lanes stay behind until
            # the next full-width wave re-uploads (see _full_wave)
            self._dev_stale = True
        else:
            self.dev = _apply_deltas(
                self.dev,
                {c: jnp.asarray(deltas[c]) for c in _LANE_COLS},
                jnp.asarray(starts), jnp.asarray(counts),
                jnp.asarray(b_shift), jnp.asarray(old_nb),
            )
            if obs.enabled():
                # the resident-splice program is a device dispatch too
                # — it runs outside any wave window (update-time), so
                # it counts globally; the spliced lane total is the
                # wave's measured divergence and rides the NEXT
                # wave.cost
                from ..obs import costmodel as _cm

                _cm.record_dispatch(f"session:splice:d{self.d_max}",
                                    site="session")
            for k in SEG_LANE_KEYS:
                self.dev[k] = jnp.asarray(np.stack(tables[k]))
        self._last_delta_lanes = int(counts.sum())
        self._last_update_full = False
        self._views = views
        self.pairs = pairs

    # ------------------------------------------------------------------
    def wave(self):
        """One merge wave over the resident state. Returns the [B]
        digest array (fetched); rank/visible stay on device as
        ``self.last_rank`` / ``self.last_visible``.

        Routing: when a delta frontier is established (a full wave ran
        and every divergent lane since stays inside the delta domain),
        the wave dispatches only the divergent window and splices the
        result into the resident weave — O(divergence) device work.
        First contact, domain violations, window-budget overflow, and
        every update()-level fallback run the full-width kernel
        instead, and a full wave re-establishes the frontier."""
        if _chaos.enabled():
            # the injectable seams: a stall fault sleeps here (the
            # heartbeat-absence wedge shape), a budget-exhaust fault
            # drops the delta frontier exactly like a real window
            # -budget exhaustion would — the declared ladder handles
            # both, bit-identically
            _chaos.stall_point("session")
            if self._delta is not None \
                    and _chaos.budget_exhaust("session"):
                obs.counter("session.delta_wave_invalidate").inc()
                if obs.enabled():
                    _recovery.step(
                        "session", "delta", "full",
                        "budget-exhaustion",
                        uuid=str(self.pairs[0][0].ct.uuid))
                self._delta = None
        if self._delta is not None:
            out = self._delta_wave()
            if out is not None:
                return out
        return self._full_wave()

    def _full_wave(self):
        """The full-width wave (first contact / fallback path): v5
        kernel + digest over the whole resident batch, then (re-)
        establish the delta frontier from its ranks."""
        from ..benchgen import LANE_KEYS5
        from ..weaver.jaxw5 import batched_merge_weave_v5

        # a full wave recomputes every lane's rank, superseding any
        # unspliced window output; and it reads the resident lanes, so
        # a deferred-splice session re-uploads from the current views
        # first (the O(doc) cost the batched path deferred)
        self._pending_window = None
        if self._dev_stale:
            self._full_upload(self.pairs)
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.wave_begin("session")
            # wedge-triage heartbeat: before the dispatch, so a live
            # monitor can pair "session wave started" with the
            # wave.digest that should follow (see parallel/wave.py)
            obs.event("run.heartbeat", stage="session.wave",
                      uuid=str(self.pairs[0][0].ct.uuid),
                      pairs=len(self.pairs))
        with obs.span("session.wave", pairs=len(self.pairs),
                      u_max=int(self.u_max)):
            r, v, _c, ov = _recovery.run_dispatch(
                "session",
                lambda: batched_merge_weave_v5(
                    *(self.dev[k] for k in LANE_KEYS5),
                    u_max=self.u_max, k_max=self.u_max,
                ))
            digest = _digest_fn()(self.dev["hi"], self.dev["lo"], r, v)
            if obs.enabled():
                from ..obs import costmodel as _cm

                _cm.record_dispatch(f"session:v5:u{int(self.u_max)}",
                                    site="session")
                _cm.record_dispatch("session:digest", site="session")
            self.last_rank = r
            self.last_visible = v
            self.last_overflow = ov
            out = np.asarray(digest)
        if obs.enabled():
            # wave-boundary devprof sample: the session's whole point
            # is device residency, so its growth must be a curve
            from ..obs import devprof

            devprof.sample_device_memory("session")
        if bool(np.asarray(ov).any()):
            rows = np.flatnonzero(np.asarray(ov)).tolist()
            if obs.enabled():
                # an overflowed wave's digests are garbage — record
                # the incident, never feed them to the monitors; the
                # cost window is dropped too (fleet.session_overflow
                # is the incident record)
                from ..obs import costmodel as _cm
                from ..obs import semantic as _sem

                _sem.session_overflow(rows)
                _cm.wave_abandon()
            raise s.CausalError(
                "wave overflowed the session's token budget; raise "
                "u_headroom or re-create the session",
                {"causes": {"token-overflow"}, "rows": rows},
            )
        if obs.enabled():
            # every session digest is device-computed (overflow raised
            # above), so the whole wave feeds the divergence monitors
            sem = _observe_semantics(self.pairs, out,
                                     np.ones(len(self.pairs), bool),
                                     "session")
            # the cost-vs-divergence join, session flavor: delta ops
            # are the lanes the LAST update actually spliced (zero
            # after a full upload — that wave paid O(doc) transfer,
            # recorded as full_bag)
            from ..obs import costmodel as _cm

            _cm.wave_cost(
                uuid=str(self.pairs[0][0].ct.uuid),
                pairs=len(self.pairs),
                lanes=2 * int(self.capacity) * len(self.pairs),
                token_budget=int(self.u_max) * len(self.pairs),
                delta_ops=self._last_delta_lanes,
                full_bag=1 if self._last_update_full else 0,
                semantic=sem,
                path="full",
            )
            self._last_delta_lanes = 0
            self._last_update_full = False
        if self._delta_enabled:
            self._establish_delta(r, v)
        self._last_digest = out
        return out

    # ----------------------------------------------- delta-native wave
    def _fail_establish(self) -> None:
        self._delta_failures += 1
        obs.counter("session.delta_establish_fail").inc()

    def _establish_delta(self, rank_dev, vis_dev) -> None:
        """Derive the delta frontier from a completed full wave: the
        shared converged lane prefix per pair, the anchor (the prefix
        weave's final node — where every divergent subtree attaches),
        the frozen prefix digest contribution, and the pow2 window
        budget. Any pair outside the domain disables the delta path
        until the next full wave (correct, just O(doc)).

        Cost discipline: the shared-prefix precheck is host-only; the
        O(doc) device rank fetch happens only after it passes, and the
        visibility fetch only after every pair's rank/domain checks
        pass. _DELTA_FAILURE_LIMIT consecutive failed establishments
        stop further attempts for this session — a fleet whose edits
        keep violating the domain must not pay the fetch per wave."""
        from ..weaver.arrays import next_pow2 as _np2
        from .mesh import mix32_np

        self._delta = None
        if self._delta_failures >= self._DELTA_FAILURE_LIMIT:
            return
        B = len(self.pairs)
        cap = self.capacity
        N = 2 * cap
        s_arr = np.zeros(B, np.int32)
        anchor_arr = np.zeros(B, np.int32)
        pdig = np.zeros(B, np.uint32)
        w_now = 0
        for r, (va, vb) in enumerate(self._views):
            sp = lanecache.shared_prefix_len(va, vb)
            if sp < 1:
                return self._fail_establish()
            s_arr[r] = sp
        rank_np = np.asarray(rank_dev)
        for r, (va, vb) in enumerate(self._views):
            sp = int(s_arr[r])
            ra = rank_np[r, :sp]
            rb = rank_np[r, cap:cap + sp]
            pr = np.minimum(ra, rb)  # the kept copy's rank per node
            # the prefix must BE the weave's prefix: its ranks are
            # exactly {0..sp-1}, once each — anything else means some
            # divergent lane wove inside it and nothing can be frozen
            if not bool((pr < sp).all()):
                return self._fail_establish()
            if int(pr.max()) != sp - 1 or \
                    int(np.bincount(pr, minlength=sp).max()) != 1:
                return self._fail_establish()
            anchor = int(np.argmax(pr))
            arena = va.arena
            if int(arena.vclass[anchor]) > 0:
                # a special anchor breaks the host-jump locality
                return self._fail_establish()
            if not (delta_domain_ok(va, sp, anchor)
                    and delta_domain_ok(vb, sp, anchor)):
                return self._fail_establish()
            anchor_arr[r] = anchor
            w_now = max(w_now, va.n - sp, vb.n - sp)
        vis_np = np.asarray(vis_dev)
        for r, (va, _vb) in enumerate(self._views):
            sp = int(s_arr[r])
            arena = va.arena
            ra = rank_np[r, :sp]
            pr = np.minimum(ra, rank_np[r, cap:cap + sp])
            keep_a = ra < N
            vis = np.where(keep_a, vis_np[r, :sp],
                           vis_np[r, cap:cap + sp])
            hi = arena.ts[:sp].astype(np.int32)
            lo = arena.spec.pack_lo(arena.site[:sp], arena.tx[:sp])
            pdig[r] = np.uint32(
                mix32_np(hi, lo, pr.astype(np.int32), vis)
                .sum(dtype=np.uint64) & np.uint64(0xFFFFFFFF))
        self._delta_failures = 0
        self._delta = {
            "s": s_arr,
            "anchor": anchor_arr,
            "prefix_digest": pdig,
            # window budget: room for the current divergence plus one
            # round's worth of appends, pow2-quantized so the window
            # program's shape survives steady-state growth; outgrowing
            # it falls back to a full wave, which re-establishes with
            # the next bucket (the "budget overflow" rebuild policy)
            "w_cap": int(_np2(max(8, w_now + 1 + self.d_max))),
        }

    def _delta_wave(self):
        """The steady-state wave: weave the divergent window only,
        splice ranks/visibility into the resident weave, and return
        digests that are bit-identical to the full wave's. Returns
        None when the dispatch overflowed (never, under the
        ``u_max = N_w`` budget rule — a safety net, not a path): the
        caller then runs the full-width wave."""
        from ..benchgen import LANE_KEYS5
        from ..weaver import jaxwd

        dstate = self._delta
        wcap = dstate["w_cap"]
        n_w = 2 * wcap
        B = len(self.pairs)
        # this wave's window covers a superset of any pending one's
        # lanes (same frontier, counts grow monotonically), so its
        # splice below supersedes the unflushed output bit-for-bit
        self._pending_window = None
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.wave_begin("session")
            obs.event("run.heartbeat", stage="session.delta_wave",
                      uuid=str(self.pairs[0][0].ct.uuid), pairs=B)
        with obs.span("session.delta_wave", pairs=B, w_cap=int(wcap)):
            with obs.span("session.delta_assemble"):
                lanes, starts, counts = assemble_delta_window(
                    self._views, dstate["s"], dstate["anchor"],
                    wcap, n_w)
            r0 = dstate["s"].astype(np.int32) - 1
            rank_w, vis_w, digest, ovf = _recovery.run_dispatch(
                "session",
                lambda: jaxwd.batched_delta_weave(
                    *(jnp.asarray(lanes[k]) for k in LANE_KEYS5),
                    jnp.asarray(dstate["prefix_digest"]),
                    jnp.asarray(r0), u_max=n_w, k_max=n_w))
            out = np.asarray(digest)
            if bool(np.asarray(ovf).any()):  # pragma: no cover -
                # structurally unreachable at u_max = N_w; kept so a
                # future budget change degrades to correct-but-slow
                obs.counter("session.delta_wave_overflow").inc()
                self._delta = None
                if obs.enabled():
                    from ..obs import costmodel as _cm

                    _recovery.step("session", "delta", "full",
                                   "window-overflow",
                                   uuid=str(self.pairs[0][0].ct.uuid))
                    _cm.wave_abandon()
                return None
            self.last_rank, self.last_visible = jaxwd.splice_ranks(
                self.last_rank, self.last_visible, rank_w, vis_w,
                jnp.asarray(starts), jnp.asarray(counts),
                jnp.asarray(r0))
            self.last_overflow = ovf
            if obs.enabled():
                from ..obs import costmodel as _cm

                _cm.record_dispatch(f"session:delta:w{int(wcap)}",
                                    site="session")
                _cm.record_dispatch("session:delta_splice",
                                    site="session")
        if obs.enabled():
            from ..obs import devprof

            devprof.sample_device_memory("session")
            sem = _observe_semantics(self.pairs, out,
                                     np.ones(B, bool), "session")
            from ..obs import costmodel as _cm

            _cm.wave_cost(
                uuid=str(self.pairs[0][0].ct.uuid),
                pairs=B,
                lanes=2 * int(self.capacity) * B,
                tokens=int(counts.sum()) + 2 * B,
                token_budget=int(n_w) * B,
                delta_ops=self._last_delta_lanes,
                full_bag=0,
                semantic=sem,
                path="delta",
            )
            self._last_delta_lanes = 0
            self._last_update_full = False
        self._last_digest = out
        return out

    # ------------------------------------------ batched-serving hooks
    #
    # The assemble→dispatch→splice pipeline of _delta_wave, factored
    # so an external scheduler (serve.batch.BatchScheduler) can stack
    # MANY sessions' windows as rows of ONE device program per pow2
    # bucket: window_pack() hands out the host-side window spec,
    # complete_window() absorbs this session's rows of the bucket
    # dispatch's output, and the rank/visibility splice is deferred
    # (_flush_window) until something actually reads the resident
    # weave — so N tenants' waves cost one dispatch per bucket, not
    # three per tenant.

    @property
    def bucket_key(self) -> int:
        """The pow2 batch-bucket key: the established window budget,
        or 0 when the next wave must run full width (no frontier)."""
        return int(self._delta["w_cap"]) if self._delta is not None \
            else 0

    def window_pack(self):
        """The host-side delta-window spec _delta_wave would assemble,
        for an external batch scheduler: the current views, the frozen
        frontier arrays, and the pow2 window budget (the bucket key).
        None when no frontier is established — the caller falls back
        to :meth:`wave` (full-width re-establish)."""
        if self._delta is None:
            return None
        dstate = self._delta
        return {
            "views": self._views,
            "s": dstate["s"],
            "anchor": dstate["anchor"],
            "prefix_digest": dstate["prefix_digest"],
            "w_cap": int(dstate["w_cap"]),
            "rows": len(self.pairs),
        }

    def abandon_frontier(self, reason: str, site: str = "serve"):
        """Drop the delta frontier with recovery-ladder evidence: the
        batched scheduler's per-tenant fallback rung (bucket window
        overflow, injected budget exhaustion). The next wave runs full
        width and re-establishes — this tenant alone pays the slow
        path, its bucket-mates stay fast."""
        if self._delta is None:
            return
        obs.counter("session.delta_wave_invalidate").inc()
        if obs.enabled():
            _recovery.step(site, "batch", "full", reason,
                           uuid=str(self.pairs[0][0].ct.uuid))
        self._delta = None

    def complete_window(self, rank_w, vis_w, digest, starts, counts):
        """Absorb this session's rows of a bucket dispatch's output
        (host arrays, already fetched once for the whole bucket). The
        digests are bit-identical to what _delta_wave would have
        returned — same window assembly, same program, same budget —
        so they become the checkpointable wave output directly; the
        rank/visibility splice is deferred to :meth:`_flush_window`
        (checkpoint/merged) because the next wave's window covers a
        superset of these lanes anyway."""
        dstate = self._delta
        if dstate is None:
            raise s.CausalError(
                "complete_window without an established frontier",
                {"causes": {"no-frontier"}},
            )
        out = np.asarray(digest)
        self._pending_window = {
            "rank_w": np.asarray(rank_w),
            "vis_w": np.asarray(vis_w),
            "starts": np.asarray(starts, np.int32),
            "counts": np.asarray(counts, np.int32),
            "r0": dstate["s"].astype(np.int32) - 1,
        }
        if obs.enabled():
            # per-tenant semantics are unchanged by batching: the
            # wave.digest agreement, staleness and lag resolution all
            # observe THIS session's digests, same as _delta_wave
            _observe_semantics(self.pairs, out,
                               np.ones(len(self.pairs), bool),
                               "session")
        self._last_digest = out
        return out

    def _flush_window(self):
        """Splice the pending window output into the resident
        rank/visibility arrays. Deferred from complete_window: in the
        batched steady state N waves pass between materializations,
        and each window supersedes the last, so the splice runs once
        per read instead of once per wave."""
        pw = self._pending_window
        if pw is None:
            return
        self._pending_window = None
        from ..weaver import jaxwd

        self.last_rank, self.last_visible = jaxwd.splice_ranks(
            self.last_rank, self.last_visible,
            jnp.asarray(pw["rank_w"]), jnp.asarray(pw["vis_w"]),
            jnp.asarray(pw["starts"]), jnp.asarray(pw["counts"]),
            jnp.asarray(pw["r0"]))
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.record_dispatch("session:delta_splice",
                                site="session")

    def pop_divergence(self):
        """(delta_lanes, full_bag) shipped since the last wave — the
        wave.cost divergence evidence, reset on read. The batched
        scheduler drains every bucket member and sums them onto the
        bucket's single wave.cost event."""
        d = int(self._last_delta_lanes)
        f = 1 if self._last_update_full else 0
        self._last_delta_lanes = 0
        self._last_update_full = False
        return d, f

    def converge(self, tree: bool = True,
                 w_budget: Optional[int] = None):
        """Converge the WHOLE resident fleet — every replica of every
        pair — into one host handle.

        The session's waves converge pairs; fleet-wide convergence is
        a reduction over all 2B replicas, and its default shape is the
        merge reduction tree (``parallel.tree``): ceil(log2(2B))
        batched device rounds, level 0 full width, later levels riding
        the delta window path, bit-identical to any pairwise fold.
        ``tree=False`` runs that flat fold instead — n-1 SEQUENTIAL
        pairwise waves with per-step host materialization, the O(n)
        baseline the tree replaces (kept as the A/B control and the
        escape hatch). The session's device-resident pair state is
        untouched either way: convergence reads the handles, it does
        not re-upload them."""
        from . import tree as _tree

        replicas = [h for pair in self.pairs for h in pair]
        if tree:
            return _tree.merge_tree(replicas, w_budget=w_budget)
        return _tree.flat_fold(replicas)

    def merged(self, i: int):
        """Materialize pair ``i``'s converged tree (host handle) from
        the last wave."""
        from .wave import WaveResult

        self._flush_window()
        res = WaveResult(
            self.pairs, self._views, self.capacity,
            np.asarray(self.last_rank), np.asarray(self.last_visible),
            np.zeros(len(self.pairs), np.uint32), {}, "v5",
        )
        return res.merged(i)

    # --------------------------------------------- checkpoint/restore

    CHECKPOINT_VERSION = 1

    def checkpoint(self) -> dict:
        """Serialize the session's resident state to one JSON-able
        dict: the replica pairs (serde's tagged node-bag encoding),
        the last wave's rank/visibility/digest arrays, and the delta
        frontier. A process that crashes after a checkpoint restores
        with :meth:`restore` and resumes STEADY-STATE DELTA WAVES —
        no full-width re-weave, no O(doc) frontier re-establishment
        fetch; the restore pays one lane upload plus one digest
        dispatch (the bit-identity gate). Requires at least one
        completed wave (the checkpointed state IS a wave's output)."""
        from .. import serde

        if self._last_digest is None or not hasattr(self, "last_rank"):
            raise s.CausalError(
                "nothing to checkpoint: the resident state is not a "
                "wave's output (run a wave first; an update since "
                "the last wave also invalidates it)",
                {"causes": {"no-wave"}},
            )
        self._flush_window()
        with obs.span("session.checkpoint", pairs=len(self.pairs)):
            obs.counter("session.checkpoint").inc()
            ck = {
                "~causal_session": self.CHECKPOINT_VERSION,
                "d_max": int(self.d_max),
                "u_headroom": float(self._u_headroom),
                "delta_enabled": bool(self._delta_enabled),
                "u_max": int(self.u_max),
                "capacity": int(self.capacity),
                "pairs": [[serde.to_data(a), serde.to_data(b)]
                          for a, b in self.pairs],
                "rank": _pack_arr(np.asarray(self.last_rank)),
                "visible": _pack_arr(np.asarray(self.last_visible)),
                "digest": _pack_arr(np.asarray(self._last_digest)),
            }
            if self._delta is not None:
                ck["delta"] = {
                    "s": _pack_arr(self._delta["s"]),
                    "anchor": _pack_arr(self._delta["anchor"]),
                    "prefix_digest":
                        _pack_arr(self._delta["prefix_digest"]),
                    "w_cap": int(self._delta["w_cap"]),
                }
            return ck

    def checkpoint_to(self, path: str) -> None:
        """``checkpoint()`` straight to a JSON file (atomic rename so
        a crash mid-write never leaves a torn checkpoint)."""
        import json
        import os

        blob = json.dumps(self.checkpoint())
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(blob)
            f.flush()
            # fsync BEFORE the rename: the rename can be durable
            # while the data is not, publishing a torn checkpoint —
            # and serve-side storage GC retires WAL segments on the
            # strength of this file existing
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @classmethod
    def restore(cls, data) -> "FleetSession":
        """Rebuild a session from :meth:`checkpoint` output (the dict,
        or a path to a ``checkpoint_to`` file). The restore is GATED
        on digest bit-identity: the uploaded lanes plus the restored
        rank/visibility must reproduce the checkpoint's digests
        exactly (one digest dispatch), or the restore refuses
        (``causes {"checkpoint-mismatch"}``) rather than resume from
        state it cannot prove. The delta frontier is revalidated
        host-side against the rebuilt views; if it no longer holds
        the session restores WITHOUT it (the next wave runs the full
        rung and re-establishes — correct, evidenced, just O(doc))."""
        import json as _json

        from .. import serde

        if isinstance(data, str):
            try:
                with open(data) as f:
                    data = _json.load(f)
            except ValueError as e:
                # a pack torn mid-spill (truncated JSON) refuses
                # through the same declared gate as a tampered one —
                # never a bare json error at the resume site
                raise s.CausalError(
                    "checkpoint file undecodable (torn pack?)",
                    {"causes": {"checkpoint-mismatch"},
                     "why": str(e)},
                ) from None
        if not (isinstance(data, dict)
                and data.get("~causal_session") == cls.CHECKPOINT_VERSION):
            raise s.CausalError(
                "not a FleetSession checkpoint (or unknown version)",
                {"causes": {"checkpoint-mismatch"},
                 "version": (data or {}).get("~causal_session")
                 if isinstance(data, dict) else None},
            )
        with obs.span("session.restore"):
            pairs = [(serde.from_data(ea), serde.from_data(eb))
                     for ea, eb in data["pairs"]]
            obj = cls.__new__(cls)
            obj.d_max = int(data["d_max"])
            obj._bufs = WaveBuffers()
            obj._views = []
            obj._uploaded_n = None
            obj._uploaded_k = None
            obj.capacity = 0
            # pre-seed the restored budget: _full_upload keeps the max,
            # so the session compiles the same program shapes it had
            obj.u_max = int(data["u_max"])
            obj._u_headroom = float(data["u_headroom"])
            obj.dev = None
            obj._last_delta_lanes = 0
            obj._last_update_full = False
            obj._delta_enabled = bool(data["delta_enabled"])
            obj._delta = None
            obj._delta_failures = 0
            obj._last_digest = None
            for a, b in pairs:
                s.check_mergeable(a.ct, b.ct)
            obj._full_upload(pairs)
            if obj.capacity != int(data["capacity"]):
                raise s.CausalError(
                    "checkpoint capacity mismatch (divergent rebuild)",
                    {"causes": {"checkpoint-mismatch"},
                     "expected": int(data["capacity"]),
                     "got": int(obj.capacity)},
                )
            B = len(pairs)
            try:
                rank = _unpack_arr(data["rank"])
                visible = _unpack_arr(data["visible"])
                want = _unpack_arr(data["digest"])
            except (KeyError, TypeError, ValueError) as e:
                # corrupted pack (torn base64, bad dtype): refuse
                # through the same declared gate, never a bare numpy
                # error
                raise s.CausalError(
                    "checkpoint arrays undecodable",
                    {"causes": {"checkpoint-mismatch"},
                     "why": str(e)},
                ) from None
            shape = (B, 2 * obj.capacity)
            if rank.shape != shape or visible.shape != shape \
                    or want.shape != (B,):
                raise s.CausalError(
                    "checkpoint array shapes do not match the fleet",
                    {"causes": {"checkpoint-mismatch"}},
                )
            obj.last_rank = jnp.asarray(rank)
            obj.last_visible = jnp.asarray(visible)
            obj.last_overflow = jnp.zeros(B, bool)
            # THE restore gate: the rebuilt lanes + the checkpointed
            # weave outputs must reproduce the checkpointed digests
            # bit-for-bit — one digest dispatch, no full wave
            got = np.asarray(_digest_fn()(
                obj.dev["hi"], obj.dev["lo"],
                obj.last_rank, obj.last_visible))
            if not np.array_equal(got, want):
                raise s.CausalError(
                    "checkpoint digest mismatch: refusing to resume "
                    "from unprovable state",
                    {"causes": {"checkpoint-mismatch"},
                     "rows": np.flatnonzero(got != want).tolist()},
                )
            obj._last_digest = got
            delta_restored = False
            dck = data.get("delta")
            if dck is not None and obj._delta_enabled:
                frontier = {
                    "s": _unpack_arr(dck["s"]),
                    "anchor": _unpack_arr(dck["anchor"]),
                    "prefix_digest": _unpack_arr(dck["prefix_digest"]),
                    "w_cap": int(dck["w_cap"]),
                }
                if obj._frontier_valid(frontier):
                    obj._delta = frontier
                    delta_restored = True
                else:
                    obs.counter("session.restore_frontier_drop").inc()
            if obs.enabled():
                _recovery.restore_recorded(
                    "session", B, delta_restored,
                    uuid=str(pairs[0][0].ct.uuid))
            return obj

    def _frontier_valid(self, frontier: dict) -> bool:
        """Host-only revalidation of a restored delta frontier against
        the freshly rebuilt views: the shared prefix still covers
        ``s``, the anchor is a live non-special lane, every divergent
        lane is still inside the delta domain, and the window fits
        the restored budget. O(divergence) numpy — never a device
        fetch."""
        w_cap = int(frontier["w_cap"])
        for r, (va, vb) in enumerate(self._views):
            sp = int(frontier["s"][r])
            anchor = int(frontier["anchor"][r])
            if sp < 1 or anchor >= sp:
                return False
            if lanecache.shared_prefix_len(va, vb) < sp:
                return False
            if int(va.arena.vclass[anchor]) > 0:
                return False
            if va.n - sp > w_cap - 1 or vb.n - sp > w_cap - 1:
                return False
            if not (delta_domain_ok(va, sp, anchor)
                    and delta_domain_ok(vb, sp, anchor)):
                return False
        return True


def _pack_arr(arr: np.ndarray) -> dict:
    """A numpy array as a compact JSON-able dict (base64 of the raw
    bytes + dtype + shape) — rank/visibility checkpoints at fleet
    scale would be absurd as JSON number lists."""
    import base64

    arr = np.ascontiguousarray(arr)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def _unpack_arr(d: dict) -> np.ndarray:
    import base64

    raw = base64.b64decode(d["b64"])
    arr = np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
    return arr.reshape([int(x) for x in d["shape"]]).copy()
