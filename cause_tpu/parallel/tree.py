"""Hierarchical merge reduction tree: fleet convergence in O(log n)
pipelined device rounds.

``merge_all`` / flat fleet convergence is a SEQUENCE: folding n
replicas through pairwise merges pays n-1 sequential wave rounds, each
a full-document-width dispatch — the dominant remaining term of the
north-star gap once the per-wave cost went delta-native (PR 7). This
module is the Tascade-shaped answer (arXiv:2311.15810, atomic-free
asynchronous reduction trees): pair the n replicas up and batch each
TREE LEVEL as one device burst —

- **level 0** (first contact) runs the full-width batched v5 kernel +
  digest over all n/2 pairs in ONE fused dispatch
  (``wave.dispatch_full_rows``), exactly the establishment policy of
  the delta-native session: the level's output ranks freeze the fleet
  frontier (the shared converged lane prefix, its weave-final anchor,
  and the prefix's exact uint32 digest contribution);
- **levels 1..L** ride PR 7's delta path: each surviving subtree is a
  *symbolic* record — the frozen prefix plus a pooled, id-sorted
  **side** of its members' divergent lanes (partial aggregation: a
  level merges two sides by one vectorized merge-dedupe, never
  re-materializing the subtree) — and a level's n/2^k pairwise merges
  become ONE ``weaver.jaxwd.batched_delta_weave`` dispatch over the
  anchor+side windows, returning each subtree's TOTAL document digest
  (prefix digest + window terms) bit-identical to a full-width weave;
- **pipelining**: a delta level's windows depend only on the pooled
  host sides, not on the previous level's device output — so the host
  merges level k+1's sides while level k executes on device, and only
  the per-level digest fetch synchronizes (the per-level convergence
  evidence the flight recorder records);
- **the root** materializes ONCE: prefix weave ++ root-window weave
  (the PR-7 factorization, n-way), with the same append-only node
  union validation any fold of ``merge`` performs. Intermediate
  winners never pay host materialization — the O(doc)-per-level
  Python cost that makes the flat fold slow.

A level whose window outgrows ``w_budget`` (or whose establishment
fails: no shared prefix, tombstoned anchor, out-of-domain causes)
**bounces** to a full-width batched level — materialize the survivors,
run the document-width kernel, re-establish — and later levels ride
delta again. Convergence is bit-identical to the pairwise fold in
every regime (the weave is a pure function of the node set; pinned in
tests/test_merge_tree.py), and every level lands ``tree.level`` +
``wave.digest(source="tree")`` semantic events and a per-level
``wave.cost`` join (``python -m cause_tpu.obs gap`` renders the
per-level decomposition).

Round count: ceil(log2(n)) levels (odd survivor counts carry a bye
lane to the next level). This is also the multi-chip seam (ROADMAP
item 3): subtrees per chip, one cross-chip root round.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from .. import chaos as _chaos
from .. import obs
from ..collections import shared as s
from ..weaver import lanecache
from ..weaver.arrays import I32_MAX, next_pow2
from ..weaver.segments import (SEG_LANE_KEYS, _TABLE_DTYPES,
                               concat_seg_tables, tree_segments)
from . import recovery as _recovery
from .wave import (WaveResult, _assemble_rows, delta_domain_ok,
                   dispatch_full_rows)

__all__ = [
    "merge_tree",
    "merge_tree_report",
    "merge_all_tree",
    "flat_fold",
    "tree_rounds",
]


def tree_rounds(n: int) -> int:
    """ceil(log2(n)) — the tree's round count for ``n`` replicas (0
    for a single replica; byes don't add rounds: survivors halve,
    rounding up, every level)."""
    return (int(n) - 1).bit_length() if n > 1 else 0


# ------------------------------------------------------------- sides


class _Side:
    """One subtree's pooled divergent lanes: id-sorted, deduped, with
    causes carried as packed ids (-1 = the anchor) so window-local
    cause indices re-derive by one searchsorted per level. ``nodes``
    parallels the lanes for root materialization."""

    __slots__ = ("keys", "hi", "lo", "vc", "cause_key", "nodes")

    def __init__(self, keys, hi, lo, vc, cause_key, nodes):
        self.keys = keys
        self.hi = hi
        self.lo = lo
        self.vc = vc
        self.cause_key = cause_key
        self.nodes = nodes

    def __len__(self) -> int:
        return len(self.keys)


def _side_of(view, sp: int, anchor: int) -> _Side:
    """The divergent side of one view w.r.t. the fleet frontier:
    lanes ``[sp, n)`` with causes re-keyed by packed id (anchor ->
    -1). Lanes are already id-sorted (arena layout); the delta-domain
    check (every cause inside the window or on the anchor) ran at
    establishment."""
    a = view.arena
    n = view.n - sp
    sl = slice(sp, view.n)
    hi = a.ts[sl].astype(np.int32)
    lo = a.spec.pack_lo(a.site[sl], a.tx[sl]).astype(np.int32)
    keys = (hi.astype(np.int64) << 32) | (lo.astype(np.int64)
                                          & 0xFFFFFFFF)
    ci = a.cause_idx[sl]
    ci_c = np.clip(ci, 0, max(0, view.n - 1))
    c_hi = a.ts[ci_c].astype(np.int64)
    c_lo = (a.spec.pack_lo(a.site[ci_c], a.tx[ci_c]).astype(np.int64)
            & 0xFFFFFFFF)
    cause_key = np.where(ci == anchor, np.int64(-1), (c_hi << 32) | c_lo)
    nodes = list(a.nodes[sp:view.n])
    if n > 1 and not bool(np.all(keys[1:] >= keys[:-1])):
        order = np.argsort(keys, kind="stable")
        keys, hi, lo = keys[order], hi[order], lo[order]
        cause_key = cause_key[order]
        vc = a.vclass[sl][order]
        nodes = [nodes[int(i)] for i in order]
        return _Side(keys, hi, lo, vc, cause_key, nodes)
    return _Side(keys, hi, lo, a.vclass[sl].copy(), cause_key, nodes)


def _merge_sides(x: _Side, y: _Side) -> _Side:
    """Partial aggregation: the level-k+1 side is one vectorized
    merge-dedupe of the two level-k sides — O(side), no subtree
    re-materialization, no per-node Python beyond the kept-node list."""
    keys = np.concatenate([x.keys, y.keys])
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    dup = np.zeros(len(ks), bool)
    dup[1:] = ks[1:] == ks[:-1]
    keep = order[~dup]

    def take(ax, ay):
        return np.concatenate([ax, ay])[keep]

    nodes_cat = x.nodes + y.nodes
    return _Side(
        ks[~dup], take(x.hi, y.hi), take(x.lo, y.lo),
        take(x.vc, y.vc), take(x.cause_key, y.cause_key),
        [nodes_cat[int(i)] for i in keep],
    )


# ---------------------------------------------------------- subtrees


class _Sub:
    """One surviving subtree. ``handle`` is set for materialized
    survivors (leaves, full-level winners); symbolic delta-level
    winners carry instead the pooled ``side`` plus the level output
    that can materialize them on demand (``ranks``/``wcap``/
    ``sides_lr`` — window ranks stay ON DEVICE until someone asks)."""

    __slots__ = ("handle", "members", "views", "side", "ranks", "wcap",
                 "sides_lr")

    def __init__(self, handle=None, members=None, views=None, side=None,
                 ranks=None, wcap=0, sides_lr=None):
        self.handle = handle
        self.members = members or ([handle] if handle is not None else [])
        self.views = views or []
        self.side = side
        self.ranks = ranks
        self.wcap = wcap
        self.sides_lr = sides_lr


def _union_nodes(members) -> dict:
    """N-way node union with the append-only validation every merge
    path shares (earlier members win the union so a conflict reports
    the body already in the merge target)."""
    nodes: dict = {}
    for h in reversed(members):
        nodes.update(h.ct.nodes)
    for h in members:
        if not (h.ct.nodes.items() <= nodes.items()):
            for nid, body in h.ct.nodes.items():
                if nodes[nid] != body:
                    raise s.CausalError(
                        "This node is already in the tree and can't "
                        "be changed.",
                        {"causes": {"append-only", "edits-not-allowed"},
                         "existing_node": (nid,) + nodes[nid]},
                    )
    return nodes


def _materialize(sub: _Sub, state: Optional[dict]):
    """A subtree's host handle: the prefix weave ++ its window weave
    (the PR-7 factorization, n-way) — paid once per materialized
    subtree, which in the steady state means ONCE, for the root."""
    if sub.handle is not None:
        return sub.handle
    assert state is not None and sub.ranks is not None
    wcap = sub.wcap
    n_w = 2 * wcap
    rw = np.asarray(sub.ranks)
    side_l, side_r = sub.sides_lr
    win_nodes: List = [None] * n_w
    for t, side in ((0, side_l), (1, side_r)):
        off = t * wcap
        for j, nd in enumerate(side.nodes):
            win_nodes[off + 1 + j] = nd
    mask = rw < n_w
    mask[0] = False
    mask[wcap] = False
    idx = np.flatnonzero(mask)
    order = idx[np.argsort(rw[idx], kind="stable")]
    prefix_order = np.argsort(state["pr"], kind="stable")
    v0 = state["prefix_view"]
    weave = [v0.arena.nodes[int(j)] for j in prefix_order]
    weave += [win_nodes[int(i)] for i in order]

    nodes = _union_nodes(sub.members)
    union = lanecache.union_views_many(sub.views)
    if union is not None and union.n != len(weave):  # pragma: no cover
        raise s.CausalError(
            "merge-tree materialization inconsistency (weave length "
            "!= union size) — please report",
            {"causes": {"tree-internal"},
             "weave": len(weave), "union": union.n},
        )
    yarns: dict = {}
    if union is not None:
        for nd in union.arena.nodes[:union.n]:
            yarns.setdefault(nd[0][1], []).append(nd)
    first = sub.members[0]
    lamport = max(
        max(h.ct.lamport_ts for h in sub.members),
        max(nid[0] for nid in nodes),
    )
    ct = first.ct.evolve(nodes=nodes, yarns=yarns, weave=weave,
                         lamport_ts=lamport, lanes=union)
    sub.handle = type(first)(ct)
    sub.members = [sub.handle]
    sub.views = [union] if union is not None else sub.views
    return sub.handle


# ----------------------------------------------------- establishment


def _establish(check_views, survivor_views, rank0, vis0, cap) -> Optional[dict]:
    """Freeze the fleet delta frontier from a completed full level:
    the shared converged lane prefix across EVERY view that feeds
    later levels, its weave-final anchor (derived from pair 0's output
    ranks, with the same permutation sanity check the delta session
    runs), and the prefix's frozen digest contribution. None when any
    check fails — later levels then run full width (correct, O(doc)).

    ``check_views`` are the level's INPUT views (pair sides + byes):
    pair 0's rank row indexes input lanes, and the delta domain must
    hold for every divergent lane that exists anywhere in the fleet.
    ``survivor_views`` are the views the sides will slice — their
    first ``s`` lanes must BE the prefix."""
    from .mesh import mix32_np

    v0 = check_views[0]
    sp = v0.n
    for v in check_views[1:]:
        sp = min(sp, lanecache.shared_prefix_len(v0, v))
        if sp < 1:
            return None
    for v in survivor_views:
        if lanecache.shared_prefix_len(v0, v) < sp:
            return None
    ra = rank0[:sp]
    rb = rank0[cap:cap + sp]
    pr = np.minimum(ra, rb).astype(np.int32)
    if not bool((pr < sp).all()):
        return None
    if int(pr.max()) != sp - 1 or \
            int(np.bincount(pr, minlength=sp).max()) != 1:
        return None
    anchor = int(np.argmax(pr))
    arena = v0.arena
    if int(arena.vclass[anchor]) > 0:
        return None
    for v in check_views:
        if not delta_domain_ok(v, sp, anchor):
            return None
    for v in survivor_views:
        if not delta_domain_ok(v, sp, anchor):
            return None
    keep_a = ra < 2 * cap
    vis = np.where(keep_a, vis0[:sp], vis0[cap:cap + sp])
    hi = arena.ts[:sp].astype(np.int32)
    lo = arena.spec.pack_lo(arena.site[:sp], arena.tx[:sp])
    pdig = int(np.uint32(
        mix32_np(hi, lo, pr, vis).sum(dtype=np.uint64)
        & np.uint64(0xFFFFFFFF)))
    return {
        "s": int(sp),
        "anchor": anchor,
        "anchor_hi": np.int32(arena.ts[anchor]),
        "anchor_lo": np.int32(arena.spec.pack_lo(
            arena.site[anchor:anchor + 1],
            arena.tx[anchor:anchor + 1])[0]),
        "pr": pr,
        "prefix_view": v0,
        "pdig": pdig,
    }


# ----------------------------------------------------- level windows


def _assemble_level(sides_pairs, state, wcap: int) -> Dict[str, np.ndarray]:
    """The level's ``[P, 2*wcap]`` delta-window batch: per pair, each
    tree is lane 0 = the anchor (presented as the window root) followed
    by that subtree's pooled side, causes re-derived into window
    coordinates by one searchsorted against the side's sorted keys.
    Host cost is O(total window lanes) — the per-level assembly the
    delta regime is allowed to pay."""
    P = len(sides_pairs)
    n_w = 2 * wcap
    hi = np.full((P, n_w), I32_MAX, np.int32)
    lo = np.full((P, n_w), I32_MAX, np.int32)
    cci = np.full((P, n_w), -1, np.int32)
    vc = np.zeros((P, n_w), np.int32)
    valid = np.zeros((P, n_w), bool)
    seg = np.full((P, n_w), -1, np.int32)
    per_row = []
    s_need = 8
    for r, (sl_, sr_) in enumerate(sides_pairs):
        per_tree = []
        for t, side in enumerate((sl_, sr_)):
            d = len(side)
            w = 1 + d
            off = t * wcap
            hi[r, off] = state["anchor_hi"]
            lo[r, off] = state["anchor_lo"]
            valid[r, off] = True
            local_cci = np.full(wcap, -1, np.int32)
            if d:
                hi[r, off + 1:off + w] = side.hi
                lo[r, off + 1:off + w] = side.lo
                vc[r, off + 1:off + w] = side.vc
                valid[r, off + 1:off + w] = True
                pos = np.searchsorted(side.keys, side.cause_key)
                local = np.where(side.cause_key < 0, 0,
                                 pos + 1).astype(np.int32)
                local_cci[1:w] = local
                cci[r, off + 1:off + w] = local + off
            segs = tree_segments(hi[r, off:off + wcap],
                                 lo[r, off:off + wcap],
                                 local_cci, vc[r, off:off + wcap], w)
            per_tree.append((segs, w))
        per_row.append(per_tree)
        s_need = max(s_need,
                     sum(sg["sg_len"].shape[0] for sg, _ in per_tree))
    s_max = next_pow2(s_need)
    tables = {k: np.zeros((P, s_max), _TABLE_DTYPES[k])
              for k in SEG_LANE_KEYS}
    for r, per_tree in enumerate(per_row):
        row_out = {k: tables[k][r] for k in SEG_LANE_KEYS}
        _t, bases = concat_seg_tables(per_tree, wcap, s_max,
                                      out=row_out)
        for t, ((segs, w), base) in enumerate(zip(per_tree, bases)):
            off = t * wcap
            seg[r, off:off + w] = segs["run_of_lane"][:w] + base
    lanes = {"hi": hi, "lo": lo, "cci": cci, "vc": vc, "valid": valid,
             "seg": seg}
    lanes.update(tables)
    return lanes


# ------------------------------------------------------- level runs


def _observe_level(uuid, level, digests, pairs, byes, delta_ops,
                   window, path, dispatches, final):
    from ..obs import lag as _lag
    from ..obs import semantic as _sem

    if not _sem.enabled():
        return None
    out = _sem.observe_tree_level(
        uuid, level, digests, [True] * len(digests), pairs=pairs,
        byes=byes, delta_ops=delta_ops, window=window, path=path,
        dispatches=dispatches, final=final)
    # convergence-lag resolution, tree flavor: level 0 weaves every
    # replica's stamped ops (create→woven); only the FINAL level's
    # fleet-wide digest agreement converges them — intermediate levels
    # converge subtrees, not the fleet
    _lag.level_observed(uuid, agreed=bool(out and out.get("agreed")),
                        level=level, final=final)
    return out


def _delta_level(pairs, state, level, uuid, byes, final):
    """One delta level: windows assembled from pooled sides, ONE fused
    ``batched_delta_weave`` dispatch for the whole level, the next
    level's sides merged on host WHILE the device executes (the
    pipeline), then one digest fetch. Returns ``(new_subs, digests,
    stats)``."""
    from ..benchgen import LANE_KEYS5
    from ..weaver import jaxwd

    sides_pairs = [(a.side, b.side) for a, b in pairs]
    wmax = max(max(len(l), len(r)) for l, r in sides_pairs)
    wcap = next_pow2(max(8, 1 + wmax))
    n_w = 2 * wcap
    P = len(pairs)
    delta_ops = sum(len(l) + len(r) for l, r in sides_pairs)
    if obs.enabled():
        from ..obs import costmodel as _cm

        _cm.wave_begin("tree")
    with obs.span("tree.level", level=level, pairs=P, wcap=int(wcap),
                  path="delta"):
        with obs.span("tree.assemble", level=level):
            lanes = _assemble_level(sides_pairs, state, wcap)
        pdig = np.full(P, np.uint32(state["pdig"]), np.uint32)
        r0 = np.full(P, state["s"] - 1, np.int32)
        rank_w, _vis_w, dig, ovf = _recovery.run_dispatch(
            "tree",
            lambda: jaxwd.batched_delta_weave(
                *(jnp.asarray(lanes[k]) for k in LANE_KEYS5),
                jnp.asarray(pdig), jnp.asarray(r0),
                u_max=int(n_w), k_max=int(n_w)))
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.record_dispatch(f"tree:delta:w{int(wcap)}", site="tree")
        # pipeline: merge the NEXT level's sides on host while the
        # window weave executes on device; the digest fetch below is
        # this level's only sync point
        new_subs = [
            _Sub(members=a.members + b.members, views=a.views + b.views,
                 side=_merge_sides(a.side, b.side), ranks=rank_w[i],
                 wcap=wcap, sides_lr=(a.side, b.side))
            for i, (a, b) in enumerate(pairs)
        ]
        digests = np.asarray(dig)
        if bool(np.asarray(ovf).any()):  # pragma: no cover -
            # structurally unreachable at u_max = N_w; a future budget
            # change degrades to the full-width bounce, never to wrong
            if obs.enabled():
                from ..obs import costmodel as _cm

                _cm.wave_abandon()
            return None
    sem = _observe_level(uuid, level, digests, P, byes, delta_ops,
                         wcap, "delta", 1, final)
    if obs.enabled():
        from ..obs import costmodel as _cm

        # lanes is the O(doc) axis (each pair's two documents: frozen
        # prefix + divergent side), tokens the O(delta) window work —
        # same units as the session's wave.cost join
        doc_lanes = sum(2 * state["s"] + len(l) + len(r)
                        for l, r in sides_pairs)
        _cm.wave_cost(uuid=uuid, pairs=P,
                      lanes=doc_lanes,
                      tokens=delta_ops + 2 * P,
                      token_budget=int(n_w) * P,
                      delta_ops=delta_ops, semantic=sem,
                      path="delta", level=level)
    stats = {"level": level, "pairs": P, "byes": byes, "path": "delta",
             "window": int(wcap), "delta_ops": int(delta_ops),
             "distinct": len(set(int(d) for d in digests)),
             "agreed": len(set(int(d) for d in digests)) == 1}
    return new_subs, digests, stats


def _full_level(pairs, state, level, uuid, byes, bye_subs, final):
    """One full-width level (first contact / bounce): materialize both
    sides of every pair, run the fused document-width kernel+digest
    over the whole level in one dispatch, materialize the winners, and
    (re-)establish the delta frontier for the levels that follow."""
    handles_pairs = [(_materialize(a, state), _materialize(b, state))
                     for a, b in pairs]
    views_pairs = []
    for ha, hb in handles_pairs:
        va = lanecache.view_for(ha.ct)
        vb = lanecache.view_for(hb.ct)
        if va is not None and vb is not None \
                and not lanecache.compatible((va, vb)):
            va = lanecache.build_view(ha.ct.nodes, ha.ct.uuid)
            vb = lanecache.build_view(hb.ct.nodes, hb.ct.uuid)
        if va is None or vb is None or not lanecache.compatible(
                (va, vb)):
            raise s.CausalError(
                "fleet outside the device domain (PackSpec overflow "
                "or map-shaped tree)",
                {"causes": {"outside-domain"}},
            )
        views_pairs.append((va, vb))
    P = len(pairs)
    cap = next_pow2(max(max(va.n, vb.n) for va, vb in views_pairs))
    if obs.enabled():
        from ..obs import costmodel as _cm

        _cm.wave_begin("tree")
    with obs.span("tree.level", level=level, pairs=P, cap=int(cap),
                  path="full"):
        with obs.span("tree.assemble", level=level):
            lanes = _assemble_rows(views_pairs, cap)
        rank, vis, dig, info = dispatch_full_rows(lanes, site="tree")
        res = WaveResult(handles_pairs, views_pairs, cap, rank, vis,
                         dig, {}, "v5",
                         digest_valid=np.ones(P, bool))
        winners = [res.merged(i) for i in range(P)]
    delta_ops = sum(
        (va.n - state["s"]) + (vb.n - state["s"])
        for va, vb in views_pairs
    ) if state is not None else 0
    sem = _observe_level(uuid, level, dig, P, byes, delta_ops,
                         cap, "full", 1 + (1 if info["retried"] else 0),
                         final)
    if obs.enabled():
        from ..obs import costmodel as _cm

        _cm.wave_cost(uuid=uuid, pairs=P, lanes=2 * int(cap) * P,
                      tokens=info["u_need"] * P,
                      token_budget=info["u_max"] * P,
                      delta_ops=delta_ops,
                      overflow_retries=info["retried"], semantic=sem,
                      path="full", level=level)
    new_subs = []
    for w, (va, vb) in zip(winners, views_pairs):
        wv = lanecache.view_for(w.ct)
        new_subs.append(_Sub(handle=w, members=[w],
                             views=[wv] if wv is not None else [va, vb]))
    # (re-)establish the frontier for the levels that follow — over
    # the level's INPUT views (the rank row indexes them) plus every
    # view that survives (winners and byes)
    check_views = [v for vp in views_pairs for v in vp]
    survivor_views = []
    bye_views = []
    for sub in new_subs:
        survivor_views.extend(sub.views)
    for sub in bye_subs:
        h = _materialize(sub, state)
        v = lanecache.view_for(h.ct)
        if v is None:
            raise s.CausalError(
                "fleet outside the device domain",
                {"causes": {"outside-domain"}},
            )
        sub.views = [v]
        bye_views.append(v)
        survivor_views.append(v)
    new_state = None
    if len(new_subs) + len(bye_subs) > 1:
        new_state = _establish(check_views + bye_views, survivor_views,
                               rank[0], vis[0], cap)
        if new_state is not None:
            sp, anchor = new_state["s"], new_state["anchor"]
            for sub in new_subs + list(bye_subs):
                sub.side = _side_of(sub.views[0], sp, anchor)
        else:
            obs.counter("tree.establish_fail").inc()
            if obs.enabled():
                # the next level cannot ride delta: declared, not
                # silent — the levels that follow run full width
                # until an establishment succeeds
                _recovery.step("tree", "delta", "full",
                               "establish-fail", uuid=uuid,
                               level=level)
    stats = {"level": level, "pairs": P, "byes": byes, "path": "full",
             "window": int(cap), "delta_ops": int(delta_ops),
             "distinct": len(set(int(d) for d in dig)),
             "agreed": len(set(int(d) for d in dig)) == 1}
    return new_subs, new_state, dig, stats


# ---------------------------------------------------------- the tree


def _merge_tree_impl(handles, w_budget: Optional[int]):
    first = handles[0]
    for h in handles[1:]:
        s.check_mergeable(first.ct, h.ct)
    uuid = str(first.ct.uuid)
    report = {"n": len(handles), "rounds": tree_rounds(len(handles)),
              "levels": []}
    if len(handles) == 1:
        return handles[0], report
    subs = [_Sub(handle=h) for h in handles]
    for sub in subs:
        v = lanecache.view_for(sub.handle.ct)
        if v is None:
            raise s.CausalError(
                "fleet outside the device domain (PackSpec overflow "
                "or map-shaped tree)",
                {"causes": {"outside-domain"}},
            )
        sub.views = [v]
    if not lanecache.compatible([sub.views[0] for sub in subs]):
        rebuilt = [lanecache.build_view(sub.handle.ct.nodes,
                                        sub.handle.ct.uuid)
                   for sub in subs]
        if any(v is None for v in rebuilt) or not lanecache.compatible(
                rebuilt):
            raise s.CausalError(
                "fleet outside the device domain",
                {"causes": {"outside-domain"}},
            )
        for sub, v in zip(subs, rebuilt):
            sub.views = [v]
    state = None
    level = 0
    with obs.span("tree.converge", n=len(handles),
                  rounds=report["rounds"]):
        while len(subs) > 1:
            pairs = [(subs[i], subs[i + 1])
                     for i in range(0, len(subs) - 1, 2)]
            bye_subs = [subs[-1]] if len(subs) % 2 else []
            byes = len(bye_subs)
            final = len(pairs) == 1 and byes == 0
            use_delta = (
                state is not None
                and all(sub.side is not None for sub in subs)
            )
            if use_delta and w_budget is not None:
                wmax = max(len(sub.side) for sub in subs)
                if 1 + wmax > int(w_budget):
                    # mid-tree full-width bounce: the pooled windows
                    # outgrew the budget — run this level at document
                    # width and re-establish for the rest (the old
                    # state stays live: the symbolic survivors still
                    # materialize through it)
                    obs.counter("tree.window_bounce").inc()
                    if obs.enabled():
                        _recovery.step("tree", "delta", "full",
                                       "window-budget", uuid=uuid,
                                       level=level)
                    use_delta = False
            if use_delta and _chaos.enabled() \
                    and _chaos.budget_exhaust("tree"):
                # injected window-budget exhaustion: identical ladder
                # rung, identical (bit-identical) full-width bounce
                obs.counter("tree.window_bounce").inc()
                if obs.enabled():
                    _recovery.step("tree", "delta", "full",
                                   "budget-exhaustion", uuid=uuid,
                                   level=level)
                use_delta = False
            if use_delta:
                out = _delta_level(pairs, state, level, uuid, byes,
                                   final)
            else:
                out = None
            if out is None:
                new_subs, state, _dig, stats = _full_level(
                    pairs, state, level, uuid, byes, bye_subs, final)
                subs = new_subs + bye_subs
            else:
                new_subs, _dig, stats = out
                subs = new_subs + bye_subs
            report["levels"].append(stats)
            level += 1
    root = _materialize(subs[0], state)
    report["path_counts"] = {
        p: sum(1 for lv in report["levels"] if lv["path"] == p)
        for p in ("full", "delta")
    }
    return root, report


def merge_tree_report(handles: Sequence, *,
                      w_budget: Optional[int] = None) -> Tuple[object, dict]:
    """Converge a fleet of list-shaped replica handles into ONE handle
    via the merge reduction tree, returning ``(root, report)`` with
    the per-level stats (``report["levels"]``: level, pairs, byes,
    path, window, delta_ops, digest agreement). See the module
    docstring; ``w_budget`` bounds the per-side window width before a
    level bounces to full document width (None = unbounded)."""
    handles = list(handles)
    if not handles:
        raise s.CausalError("Nothing to merge.",
                            {"causes": {"empty-fleet"}})
    return _merge_tree_impl(handles, w_budget)


def merge_tree(handles: Sequence, *,
               w_budget: Optional[int] = None):
    """``merge_tree_report`` without the report: the fleet's converged
    root handle, bit-identical to folding pairwise ``merge`` over the
    same replicas, in ceil(log2(n)) batched device rounds."""
    return merge_tree_report(handles, w_budget=w_budget)[0]


def merge_all_tree(handles: Sequence):
    """``merge_all``'s tree router: the converged root for >=4
    device-weaver list-shaped handles, or None when the fleet is
    outside the tree domain (map trees, pure/native weaver, PackSpec
    overflow, token overflow) — the caller then takes the flat
    ``merge_many`` path. Raises only REAL merge errors (append-only
    body conflicts, type/uuid mismatches), exactly like the fold."""
    handles = list(handles)
    if len(handles) < 4:
        return None
    if getattr(handles[0].ct, "weaver", "") != "jax":
        # pure/native users picked the host oracle: never drag the
        # device path (and a jax import) into their merge_all
        return None
    for h in handles:
        if lanecache.view_for(h.ct) is None:
            return None
    try:
        return merge_tree(handles)
    except s.CausalError as err:
        causes = set(err.info.get("causes") or ())
        if causes & {"outside-domain", "token-overflow"}:
            return None
        raise


def flat_fold(handles: Sequence, ctx=None):
    """The O(n)-round baseline the tree replaces: fold the fleet
    through n-1 SEQUENTIAL pairwise merge waves, materializing every
    intermediate winner (each step needs a host handle to feed the
    next wave). Kept as the A/B control for ``BENCH_TREE`` and
    ``FleetSession.converge(tree=False)``."""
    from .wave import merge_wave

    handles = list(handles)
    if not handles:
        raise s.CausalError("Nothing to merge.",
                            {"causes": {"empty-fleet"}})
    acc = handles[0]
    with obs.span("tree.flat_fold", n=len(handles)):
        for h in handles[1:]:
            acc = merge_wave([(acc, h)], ctx=ctx).merged(0)
    return acc
