"""Batched API-level merge waves: many replica pairs, one kernel.

This is the end-to-end north-star path (BASELINE.json config 5: 1024
divergent replica pairs of 10k-node CausalLists, p50 < 100 ms on one
chip). The reference converges a fleet by running its O(n*m) pairwise
reduce-insert once per pair (shared.cljc:300-314); here a wave of
pairs becomes ONE batched v5 segment-union dispatch whose host side is
assembly of *cached* per-tree lanes and segment tables (the lane cache,
weaver/lanecache.py) — no node-dict walking, no Python-per-node work.

Contract (deliberately device-resident, unlike the reference's eager
materialization): ``merge_wave`` returns a ``WaveResult`` holding
per-pair rank/visibility lanes and convergence digests. The converged
*state* is those lanes; turning a pair back into a host ``CausalList``
(`result.merged(i)`) is on-demand, because rebuilding 1024 Python node
dicts is host-render cost the wave itself should not pay. Fleet
control planes that only need convergence checks read the digests.

Pairs outside the accelerated domain (ids beyond the PackSpec, rank
generations that cannot be aligned, or kernel overflow rows) fall back
to the ordinary per-pair ``merge`` — same trees out, just slower.
"""

from __future__ import annotations

import itertools
import os
import time
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..collections import shared as s
from ..weaver import lanecache
from ..weaver.arrays import I32_MAX, next_pow2
from ..weaver.segments import SEG_LANE_KEYS, concat_seg_tables
from . import recovery as _recovery

__all__ = ["merge_wave", "WaveResult", "WaveBuffers",
           "delta_domain_ok", "assemble_delta_window",
           "dispatch_full_rows"]


@lru_cache(maxsize=8)
def _digest_fn():
    from .mesh import replica_digest

    return jax.jit(jax.vmap(replica_digest))


class WaveBuffers:
    """Reusable host-side assembly buffers for repeated waves.

    Allocating ~0.5 GB of [B, 2*cap] batch arrays dominates assembly
    cost at north-star scale; steady-state sync runs waves over the
    same fleet shape every round, so the buffers persist and each wave
    only rewrites the lanes that exist (plus re-padding the shrink gap
    when a row got shorter). Pass one via ``merge_wave(ctx=...)``."""

    def __init__(self):
        self.shape = None
        self.lanes = None
        self.prev_n = None   # [B, 2] lanes written last wave, per tree

    def ensure(self, B: int, cap: int, s_max: int):
        shape = (B, cap, s_max)
        N = 2 * cap
        if self.shape != shape:
            self.lanes = {
                "hi": np.full((B, N), I32_MAX, np.int32),
                "lo": np.full((B, N), I32_MAX, np.int32),
                "cci": np.full((B, N), -1, np.int32),
                "vc": np.zeros((B, N), np.int32),
                "valid": np.zeros((B, N), bool),
                "seg": np.full((B, N), -1, np.int32),
                "sg_min_hi": np.zeros((B, s_max), np.int32),
                "sg_min_lo": np.zeros((B, s_max), np.int32),
                "sg_max_hi": np.zeros((B, s_max), np.int32),
                "sg_max_lo": np.zeros((B, s_max), np.int32),
                "sg_len": np.zeros((B, s_max), np.int32),
                "sg_lane0": np.zeros((B, s_max), np.int32),
                "sg_dense": np.zeros((B, s_max), bool),
                "sg_tail_special": np.zeros((B, s_max), bool),
                "sg_valid": np.zeros((B, s_max), bool),
                "sg_vsum": np.zeros((B, s_max), np.int32),
            }
            self.prev_n = np.zeros((B, 2), np.int64)
            self.shape = shape
        return self.lanes


_PAD = {
    "hi": I32_MAX, "lo": I32_MAX, "cci": -1, "vc": 0, "valid": False,
    "seg": -1,
}


def delta_domain_ok(view, s: int, anchor: int,
                    start: Optional[int] = None) -> bool:
    """Whether lanes ``[start, view.n)`` stay inside the delta-wave
    domain for a pair whose shared converged prefix is ``[0, s)`` with
    anchor lane ``anchor`` (the prefix weave's final node):

    - every cause resolves inside the divergent window (lane >= s) or
      to the anchor itself — a cause stabbing any other resident lane
      would splice new weave positions into the frozen prefix;
    - no special (tombstone) targets the anchor — that would flip a
      frozen resident lane's visibility.

    ``start`` defaults to ``s`` (validate the whole divergent region,
    the rebuild-time call); updates validate only their appended tail.
    The check is O(lanes checked) vectorized numpy — the whole point
    is that steady-state rounds pay O(delta) here."""
    a = view.arena
    lo = s if start is None else start
    if lo >= view.n:
        return True
    ci = a.cause_idx[lo:view.n]
    ok = (ci >= s) | (ci == anchor)
    if not bool(np.all(ok)):
        return False
    if bool(np.any((a.vclass[lo:view.n] > 0) & (ci == anchor))):
        return False
    return True


def assemble_delta_window(views, s_arr, anchor_arr, wcap: int,
                          s_max: int):
    """Build the delta wave's ``[B, 2*wcap]`` window batch from cached
    views: per tree, lane 0 is the anchor (presented as the window
    root: cause -1) followed by the divergent-suffix lanes
    ``[s, n)``, causes remapped into window coordinates (anchor -> 0,
    window lane ``j`` -> ``j - s + 1``). Returns ``(lanes, starts,
    counts)`` with ``lanes`` the ``benchgen.LANE_KEYS5`` dict and
    ``starts``/``counts`` the [B, 2] per-tree shared-prefix length and
    divergent lane count the splice program consumes; ``lanes`` holds
    every ``benchgen.LANE_KEYS5`` key. Host cost is O(total window
    lanes) — the per-wave assembly the delta path is allowed to pay."""
    from ..weaver.segments import _TABLE_DTYPES, tree_segments

    B = len(views)
    Nw = 2 * wcap
    hi = np.full((B, Nw), I32_MAX, np.int32)
    lo = np.full((B, Nw), I32_MAX, np.int32)
    cci = np.full((B, Nw), -1, np.int32)
    vc = np.zeros((B, Nw), np.int32)
    valid = np.zeros((B, Nw), bool)
    seg = np.full((B, Nw), -1, np.int32)
    tables = {k: np.zeros((B, s_max), _TABLE_DTYPES[k])
              for k in SEG_LANE_KEYS}
    starts = np.zeros((B, 2), np.int32)
    counts = np.zeros((B, 2), np.int32)
    for r, (va, vb) in enumerate(views):
        s = int(s_arr[r])
        anchor = int(anchor_arr[r])
        per_tree = []
        for t, v in enumerate((va, vb)):
            a = v.arena
            d = v.n - s
            w = 1 + d
            off = t * wcap
            hi[r, off] = np.int32(a.ts[anchor])
            lo[r, off] = a.spec.pack_lo(a.site[anchor:anchor + 1],
                                        a.tx[anchor:anchor + 1])[0]
            valid[r, off] = True
            if d:
                sl = slice(s, v.n)
                hi[r, off + 1:off + w] = a.ts[sl]
                lo[r, off + 1:off + w] = a.spec.pack_lo(a.site[sl],
                                                        a.tx[sl])
                ci = a.cause_idx[sl]
                local = np.where(ci == anchor, 0,
                                 ci - s + 1).astype(np.int32)
                cci[r, off + 1:off + w] = local + off
                vc[r, off + 1:off + w] = a.vclass[sl]
                valid[r, off + 1:off + w] = True
            local_cci = np.full(wcap, -1, np.int32)
            if d:
                local_cci[1:w] = np.where(ci == anchor, 0, ci - s + 1)
            segs = tree_segments(hi[r, off:off + wcap],
                                 lo[r, off:off + wcap],
                                 local_cci, vc[r, off:off + wcap], w)
            per_tree.append((segs, w))
            starts[r, t] = s
            counts[r, t] = d
        row_out = {k: tables[k][r] for k in SEG_LANE_KEYS}
        _t, bases = concat_seg_tables(per_tree, wcap, s_max,
                                      out=row_out)
        for t, ((segs, w), base) in enumerate(zip(per_tree, bases)):
            off = t * wcap
            seg[r, off:off + w] = segs["run_of_lane"][:w] + base
    lanes = {"hi": hi, "lo": lo, "cci": cci, "vc": vc, "valid": valid,
             "seg": seg}
    lanes.update(tables)
    return lanes, starts, counts


def dispatch_full_rows(lanes, site: str = "tree"):
    """One fused full-width kernel+digest dispatch over an assembled
    ``[B, 2*cap]`` v5 lane batch (``benchgen.LANE_KEYS5`` dict), with
    the pow2-quantized token budget and a doubled-budget retry for
    spiky unsampled rows — the level primitive the merge reduction
    tree (``parallel.tree``) shares with the sweep/harvest gates.

    Returns ``(rank, visible, digest, info)`` as numpy arrays plus an
    ``info`` dict (``u_need``/``u_max``/``retried`` — the caller's
    ``wave.cost`` evidence). Raises ``CausalError`` if a row still
    overflows at the doubled budget (unlike ``merge_wave`` there is no
    per-pair host fallback here: the caller owns the batch)."""
    from ..benchgen import LANE_KEYS5, v5_token_budget
    from ..weaver.jaxwd import batched_weave_digest

    u_need = int(v5_token_budget(lanes))
    u_max = next_pow2(u_need)

    def _run(sub, u):
        out = _recovery.run_dispatch(
            site,
            lambda: batched_weave_digest(
                *(jnp.asarray(sub[k]) for k in LANE_KEYS5),
                u_max=int(u), k_max=int(u)))
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.record_dispatch(f"{site}:full:u{int(u)}", site=site)
        return tuple(np.asarray(x) for x in out)

    rank, visible, digest, overflow = _run(lanes, u_max)
    retried = 0
    if overflow.any():
        rows = np.flatnonzero(overflow)
        retried = len(rows)
        obs.counter("wave.overflow_retry").inc(retried)
        if obs.enabled():
            # recovery-ladder rung: the sampled token budget missed a
            # spiky row, escalate just those rows to a doubled budget
            _recovery.step(site, "full", "double_budget",
                           "token-overflow", rows=retried)
        sub = {k: lanes[k][rows] for k in LANE_KEYS5}
        r2, v2, d2, ov2 = _run(sub, 2 * u_max)
        if ov2.any():
            raise s.CausalError(
                "full-width level overflowed its doubled token budget",
                {"causes": {"token-overflow"},
                 "rows": np.flatnonzero(ov2).tolist()},
            )
        rank = np.array(rank)
        visible = np.array(visible)
        digest = np.array(digest)
        rank[rows] = r2
        visible[rows] = v2
        digest[rows] = d2
    return rank, visible, digest, {
        "u_need": u_need, "u_max": int(u_max), "retried": retried,
    }


def _observe_semantics(pairs, digests, valid, source: str):
    """One wave's CRDT-semantic telemetry (``wave.digest`` agreement,
    per-pair staleness, ``divergence`` provenance) — obs-on callers
    only. The version-vector callback is lazy: vectors are built from
    the yarn caches only when a divergence actually needs
    first-differing-site provenance, never on the agreeing fast
    path. Returns the wave summary fields (``observe_wave``'s dict)
    so the cost model can join them onto its ``wave.cost`` event, or
    None when obs is off."""
    from ..obs import lag as _lag
    from ..obs import semantic
    from ..sync import version_vector

    if not semantic.enabled():
        return None

    def vv_of(i):
        # the merged pair's vector: pointwise max of both replicas'
        a, b = pairs[i]
        vv = {k: list(v) for k, v in version_vector(a).items()}
        for site, h in version_vector(b).items():
            h = list(h)
            if site not in vv or vv[site] < h:
                vv[site] = h
        return vv

    sem = semantic.observe_wave(pairs[0][0].ct.uuid, digests, valid,
                                vv_of=vv_of, source=source)
    # convergence-lag resolution: the wave wove every op stamped for
    # this document (create→woven), and an agreeing wave is the
    # fleet-converged visibility point (create→converged); a
    # disagreeing or degenerate wave leaves the ops pending — they
    # resolve at the first wave whose digests agree
    _lag.wave_observed(pairs[0][0].ct.uuid,
                       agreed=bool(sem and sem.get("agreed")),
                       source=source)
    return sem

# Lanes sampled per tree per wave by the body spot-check below.
# CAUSE_TPU_BODY_SAMPLE=0 disables; a value >= the tree size checks
# every lane (what the adversarial tests use).
_BODY_SAMPLE = int(os.environ.get("CAUSE_TPU_BODY_SAMPLE", "16") or 0)
_wave_seq = itertools.count()


def _sampled_body_spotcheck(views, k: Optional[int] = None) -> dict:
    """Close the device value-byte blind spot probabilistically.

    The kernels dedupe twin segments by ids/classes/structure; host
    VALUE bytes never reach the device (jaxw5 module caveat), so two
    replicas sharing an id but differing in its body — an append-only
    violation from a corrupt replica (reference rule:
    shared.cljc:169-171) — would pass the device-only wave/digest
    paths silently. ``WaveResult.merged`` validates fully, but fleets
    that read only digests never call it.

    Returns ``{pair_index: CausalError}`` for the violating pairs
    (the caller quarantines them; raising here would fail every
    healthy pair in the wave — round-4 advisor finding #1).

    This check samples ``k`` random lanes per tree per wave and
    compares bodies with the twin via its O(1) ``lane_of`` index —
    O(k) per pair instead of O(shared base), which is the entire point
    of the segment-union design. Samples rotate each wave (counter
    -seeded RNG), so repeated waves over a fleet accumulate coverage;
    at the north-star scale one wave already draws ~16k samples.
    """
    k = _BODY_SAMPLE if k is None else k
    bad: dict = {}
    if k <= 0:
        return bad
    # fresh entropy + a session counter: samples must differ both
    # across waves in one process AND across process restarts, or the
    # promised coverage accumulation never happens for one-wave-per
    # -process deployments (CLI sync rounds)
    rng = np.random.default_rng(
        [os.getpid(), time.time_ns() & 0xFFFFFFFF, next(_wave_seq)]
    )
    for pair_idx, (va, vb) in enumerate(views):
        for side, (src, dst) in enumerate(((va, vb), (vb, va))):
            ns, nd = src.n, dst.n
            if not ns or not nd:
                continue
            lanes = (range(ns) if k >= ns
                     else rng.integers(0, ns, size=k))
            sn, dn = src.arena.nodes, dst.arena.nodes
            d_lane = dst.arena.lane_of
            for ln in lanes:
                nid, cause, value = sn[int(ln)]
                j = d_lane.get(nid)
                if (j is not None and j < nd
                        and (dn[j][1] != cause or dn[j][2] != value)):
                    # same convention as check_no_conflicting_bodies:
                    # existing_node is the merge TARGET's body (dst);
                    # plus enough context to quarantine the replica.
                    # Collected per pair (round-4 advisor finding #1):
                    # one corrupt replica must poison ITS pair, not
                    # the other 1023 in the wave
                    bad[pair_idx] = s.CausalError(
                        "This node is already in the tree and can't "
                        "be changed.",
                        {"causes": {"append-only", "edits-not-allowed"},
                         "existing_node": (nid,) + tuple(dn[j][1:]),
                         "conflicting_node": (nid, cause, value),
                         "pair": pair_idx,
                         "conflicting_side": "a" if side == 0 else "b"},
                    )
                    break
            if pair_idx in bad:
                break
    return bad


def _assemble_rows(views: Sequence[Tuple["lanecache.LaneView",
                                         "lanecache.LaneView"]],
                   cap: int, bufs: Optional[WaveBuffers] = None):
    """[B, 2*cap] v5 lane batch + segment tables from cached views.
    Pure numpy copies of cached arrays — the per-wave host cost. With
    ``bufs``, batch arrays are reused across waves and only live lanes
    (plus any shrink gap vs the previous wave) are rewritten."""
    B = len(views)
    per_row_segs = [
        [(va.segments(), va.n), (vb.segments(), vb.n)]
        for va, vb in views
    ]
    s_max = next_pow2(max(
        sum(sg["sg_len"].shape[0] for sg, _ in row) for row in per_row_segs
    ))
    bufs = bufs or WaveBuffers()
    lanes = bufs.ensure(B, cap, s_max)
    hi, lo, cci = lanes["hi"], lanes["lo"], lanes["cci"]
    vc, valid, seg = lanes["vc"], lanes["valid"], lanes["seg"]
    for r, (va, vb) in enumerate(views):
        # segment tables: the shared layout helper writes straight into
        # this row's (reused) buffer views
        row_out = {k: lanes[k][r] for k in SEG_LANE_KEYS}
        _t, bases = concat_seg_tables(per_row_segs[r], cap,
                                      s_max, out=row_out)
        for t, v in enumerate((va, vb)):
            v.arena.sync_ranks()
            a, n = v.arena, v.n
            off = t * cap
            sl = slice(off, off + n)
            hi[r, sl] = a.ts[:n]
            lo[r, sl] = a.spec.pack_lo(a.site[:n], a.tx[:n])
            ci = a.cause_idx[:n]
            cci[r, sl] = np.where(ci >= 0, ci + off, -1)
            vc[r, sl] = a.vclass[:n]
            valid[r, sl] = True
            segs = per_row_segs[r][t][0]
            seg[r, sl] = segs["run_of_lane"][:n] + bases[t]
            prev = int(bufs.prev_n[r, t])
            if prev > n:  # re-pad the shrink gap
                gap = slice(off + n, off + prev)
                for key, pad in _PAD.items():
                    lanes[key][r, gap] = pad
            bufs.prev_n[r, t] = n
    return lanes


class WaveResult:
    """One wave's converged device state plus lazy host materialization.

    - ``digest``: [B] uint32 per-pair weave digests (equal digests =>
      identical converged linearizations; see mesh.replica_digest) —
      ONLY where ``digest_valid`` is True. digest_valid is False for
      TWO distinct categories a digest-only consumer must check
      separately: ``fallback`` rows (host path ran; compare their
      ``merged`` trees instead) and ``poisoned`` rows (a corrupt
      replica was caught — see the ``poisoned`` property for the
      sources; ``merged(i)`` raises that pair's CausalError — these
      rows have NO valid result);
    - ``rank``/``visible``: [B, 2*cap] per-concat-lane outputs of the
      v5 kernel (rank == 2*cap for dropped/duplicate/padding lanes);
    - ``merged(i)``: the converged CausalList of pair i as a host
      handle — identical to ``pairs[i][0].merge(pairs[i][1])``,
      including the append-only body validation (conflicting duplicate
      ids raise CausalError exactly like a merge would);
    - ``fallback``: indices of pairs that ran the host path instead
      (outside the device domain or kernel overflow).
    """

    def __init__(self, pairs, views, cap, rank, visible, digest,
                 fallback_results, kernel, digest_valid=None,
                 poisoned=None):
        self._pairs = pairs
        self._views = views
        self.capacity = cap
        self.rank = rank
        self.visible = visible
        self.digest = digest
        self.digest_valid = (
            digest_valid if digest_valid is not None
            else np.zeros(len(pairs), bool)
        )
        self._fallback = fallback_results  # {index: merged_handle}
        self._poisoned = poisoned or {}    # {index: CausalError}
        self.kernel = kernel

    @property
    def fallback(self):
        return sorted(self._fallback)

    @property
    def poisoned(self):
        """Pairs quarantined with their own CausalError — the rest of
        the wave is valid; ``merged(i)`` raises the pair's error
        (round-4 advisor finding #1). Three sources: the sampled body
        spot-check on device rows (probabilistic — CAUSE_TPU_BODY_SAMPLE
        tunes/disables it), and the merge-time validation of host
        fallback and overflow rows (deterministic — those pairs run
        ``a.merge(b)`` eagerly, so a corrupt replica there is caught
        even with sampling off)."""
        return sorted(self._poisoned)

    def __len__(self):
        return len(self._pairs)

    def merged(self, i: int):
        """Materialize pair ``i``'s converged tree as a host handle."""
        if i in self._poisoned:
            raise self._poisoned[i]
        if i in self._fallback:
            return self._fallback[i]
        a, b = self._pairs[i]
        va, vb = self._views[i]
        cap = self.capacity
        rank_row = self.rank[i]
        keep = np.flatnonzero(rank_row < 2 * cap)
        order = keep[np.argsort(rank_row[keep], kind="stable")]
        an, bn = va.arena.nodes, vb.arena.nodes

        def node_at(lane):
            return an[lane] if lane < cap else bn[lane - cap]

        weave = [node_at(int(j)) for j in order]
        union = lanecache.union_views(va, vb)
        nodes = dict(a.ct.nodes)
        # the same append-only validation a.merge(b) runs: a duplicate
        # id with a different body must raise, never yield a
        # weave/nodes-inconsistent tree
        s.check_no_conflicting_bodies(nodes, b.ct.nodes)
        nodes.update(b.ct.nodes)
        yarns = {}
        if union is not None:
            for nd in union.arena.nodes[: union.n]:
                yarns.setdefault(nd[0][1], []).append(nd)
        else:  # pragma: no cover - compatible views built by merge_wave
            for nid in sorted(nodes):
                yarns.setdefault(nid[1], []).append(
                    (nid, nodes[nid][0], nodes[nid][1])
                )
        lamport = max(a.ct.lamport_ts, b.ct.lamport_ts,
                      max(nid[0] for nid in nodes))
        ct = a.ct.evolve(
            nodes=nodes, yarns=yarns, weave=weave, lamport_ts=lamport,
            lanes=union,
        )
        return type(a)(ct)


def merge_wave(pairs: Sequence[Tuple[object, object]],
               mesh=None, ctx: Optional[WaveBuffers] = None) -> WaveResult:
    """Merge every (a, b) replica pair in one batched device dispatch.

    All pairs must be list-shaped handles; each pair shares a uuid/type
    (the usual merge guards). With ``mesh``, the replica axis shards
    over it (sharded_merge_weave_v5) — the batch must divide the mesh
    size. Body validation between duplicate ids follows the device
    contract (jaxw5 module caveat): run ``shared.union_nodes`` or the
    per-pair ``merge`` path when untrusted replicas are involved.
    """
    pairs = list(pairs)
    if not pairs:
        raise s.CausalError("Nothing to merge.", {"causes": {"empty-fleet"}})
    with obs.span("wave.merge", pairs=len(pairs),
                  sharded=mesh is not None):
        return _merge_wave(pairs, mesh, ctx)


def _merge_wave(pairs, mesh, ctx) -> WaveResult:
    if obs.enabled():
        # open the wave cost window: device program invocations below
        # attribute to it, and ONE wave.cost event joins them to the
        # wave's divergence evidence on every exit path
        from ..obs import costmodel as _cm

        _cm.wave_begin("wave")
        # wedge triage heartbeat (PR 10): lands BEFORE the device
        # dispatch, so a live monitor distinguishes "a wave started
        # and never produced its wave.digest" (wedged dispatch) from
        # "nobody is waving" (idle) — the obs watch absence rules
        # read exactly this pairing
        obs.event("run.heartbeat", stage="wave",
                  uuid=str(pairs[0][0].ct.uuid), pairs=len(pairs))
    for a, b in pairs:
        s.check_mergeable(a.ct, b.ct)

    from .. import sync as _sync

    quarantine_live = _sync.any_quarantined()
    views: List[Optional[Tuple[object, object]]] = []
    fallback = {}
    poisoned: dict = {}
    for i, (a, b) in enumerate(pairs):
        if quarantine_live and (
                _sync.is_quarantined(a.ct.site_id)
                or _sync.is_quarantined(b.ct.site_id)):
            # a quarantined replica is OUT of the device wave: its
            # pair runs the host merge, whose full append-only body
            # validation is exactly what a repeat payload offender
            # has to pass — a corrupt one lands in poisoned below,
            # never in the digest-only device path
            obs.counter("wave.quarantined").inc()
            if obs.enabled():
                _recovery.step("wave", "full", "host", "quarantined",
                               uuid=str(a.ct.uuid), pair=i)
            try:
                fallback[i] = a.merge(b)
            except s.CausalError as err:
                err.info["pair"] = i
                poisoned[i] = err
            views.append(None)
            continue
        # view_for returns None for map trees (they need the mapw
        # forest encoding) and off-domain ids: both take the correct
        # per-pair host merge below
        va = lanecache.view_for(a.ct)
        vb = lanecache.view_for(b.ct)
        if va is not None and vb is not None and not lanecache.compatible(
                (va, vb)):
            # stale rank generation on one side: rebuild both fresh
            va = lanecache.build_view(a.ct.nodes, a.ct.uuid)
            vb = lanecache.build_view(b.ct.nodes, b.ct.uuid)
        if va is None or vb is None or not lanecache.compatible((va, vb)):
            try:
                fallback[i] = a.merge(b)
            except s.CausalError as err:
                # the per-pair quarantine contract covers the host
                # fallback path too: a corrupt replica that is ALSO
                # off the device domain must poison its own pair, not
                # abort the other pairs' wave (mergeability of every
                # pair was already checked wave-wide above — what can
                # raise here is the merge-time body validation)
                err.info["pair"] = i
                poisoned[i] = err
            views.append(None)
        else:
            views.append((va, vb))

    live = [i for i, v in enumerate(views) if v is not None]
    # device paths never see host value bytes; the sampled host-side
    # check quarantines corrupt PAIRS (merged(i) raises for them
    # alone) instead of failing the healthy rest of the wave
    if live:
        bad = _sampled_body_spotcheck([views[i] for i in live])
        for local_idx, err in bad.items():
            i = live[local_idx]
            # the spot-check saw the COMPACTED live list; remap its
            # pair index to the wave's, or a caller quarantining by
            # info["pair"] would hit a healthy pair whenever a
            # fallback pair precedes the corrupt one
            err.info["pair"] = i
            poisoned[i] = err
            views[i] = None
        live = [i for i, v in enumerate(views) if v is not None]
    if not live:
        B = len(pairs)
        obs.counter("wave.pairs").inc(B)
        obs.counter("wave.fallback").inc(len(fallback))
        obs.counter("wave.poisoned").inc(len(poisoned))
        if obs.enabled():
            # the wave still happened; every pair ages (no device
            # digest converged it against the fleet's modal value)
            sem = _observe_semantics(pairs, np.zeros(B, np.uint32),
                                     np.zeros(B, bool), "wave")
            # a degenerate wave (all pairs host-merged/poisoned) ran
            # zero device programs: its wave.cost records that — the
            # "dispatches >= 1" invariant holds for non-degenerate
            # waves only
            from ..obs import costmodel as _cm

            _cm.wave_cost(uuid=str(pairs[0][0].ct.uuid), pairs=B,
                          lanes=0, full_bag=len(fallback),
                          poisoned=len(poisoned), semantic=sem,
                          path="full")
        return WaveResult(pairs, views, 0,
                          np.zeros((B, 0), np.int32),
                          np.zeros((B, 0), bool),
                          np.zeros(B, np.uint32), fallback, "host",
                          poisoned=poisoned)

    cap = next_pow2(max(
        max(va.n, vb.n) for i in live for va, vb in [views[i]]
    ))
    live_views = [views[i] for i in live]
    if mesh is not None and len(live_views) % mesh.size:
        # fallbacks shrank the batch below mesh divisibility: pad with
        # copies of the first live row and drop their outputs below
        pad_rows = (-len(live_views)) % mesh.size
        live_views = live_views + [live_views[0]] * pad_rows
    with obs.span("wave.assemble", rows=len(live_views), cap=int(cap)):
        lanes = _assemble_rows(live_views, cap, bufs=ctx)

    from ..benchgen import LANE_KEYS5, v5_token_budget

    # BENCH_KERNEL routes the wave's kernel variant (the api-level
    # twin of bench.py's forced-kernel knob), for the v5 family only
    # — the wave path is segment-union by design. Unknown values fail
    # loudly (bench.py contract): a typo must not silently time v5.
    import os as _os

    forced = _os.environ.get("BENCH_KERNEL", "").strip()
    if forced not in ("", "v5", "v5w", "v5f"):
        raise ValueError(
            f"merge_wave supports BENCH_KERNEL of v5/v5w/v5f only "
            f"(the wave path is segment-union); got {forced!r}")
    pipeline = forced or "v5"

    def dispatch_v5(sub_lanes, u):
        """Batched v5-family dispatch + device digest, one scalar-free
        host fetch."""
        if pipeline == "v5f":
            from ..weaver.jaxw5f import batched_merge_weave_v5f

            def _batched(*a, u_max, k_max):
                return batched_merge_weave_v5f(
                    *a, u_max=u_max, k_max=k_max)
        else:
            from ..weaver.jaxw5 import batched_merge_weave_v5

            def _batched(*a, u_max, k_max):
                return batched_merge_weave_v5(
                    *a, u_max=u_max, k_max=k_max,
                    euler="walk" if pipeline == "v5w" else "doubling")

        r, v, _c, ov = _recovery.run_dispatch(
            "wave",
            lambda: _batched(
                *(jnp.asarray(sub_lanes[k]) for k in LANE_KEYS5),
                u_max=u, k_max=u,
            ))
        d = _digest_fn()(jnp.asarray(sub_lanes["hi"]),
                         jnp.asarray(sub_lanes["lo"]), r, v)
        if obs.enabled():
            # dispatch accounting: one kernel invocation plus one
            # digest invocation per dispatch_v5 call, attributed to
            # the open wave window
            from ..obs import costmodel as _cm

            _cm.record_dispatch(f"wave:{pipeline}:u{int(u)}",
                                site="wave")
            _cm.record_dispatch("wave:digest", site="wave")
        return (np.asarray(r), np.asarray(v), np.asarray(d),
                np.asarray(ov))

    # pow2-quantized budget: every distinct u_max is a distinct XLA
    # program, so exact budgets would recompile on every wave whose
    # divergence shifted slightly
    u_need = int(v5_token_budget(lanes))
    u_max = next_pow2(u_need)
    if obs.enabled():
        # token-budget headroom: the pow2 slack this fleet has before
        # a divergence spike overflows the kernel and forces
        # retries/host fallbacks
        from ..obs import semantic as _sem

        _sem.token_headroom(int(u_max) - u_need, "wave")
    with obs.span("wave.dispatch", kernel=pipeline,
                  rows=len(live_views), u_max=int(u_max),
                  sharded=mesh is not None):
        if mesh is not None:
            from .mesh import sharded_merge_weave_v5

            if pipeline == "v5w":
                raise ValueError(
                    "BENCH_KERNEL=v5w has no sharded wave step; use "
                    "v5 or v5f under a mesh")
            jl = {k: jnp.asarray(v) for k, v in lanes.items()}
            rank, visible, overflow, digest, _tv, _nc, _n_ov = (
                sharded_merge_weave_v5(mesh, jl, u_max=u_max,
                                       k_max=u_max, pipeline=pipeline)
            )
            rank = np.asarray(rank)
            visible = np.asarray(visible)
            digest = np.asarray(digest)
            overflow = np.asarray(overflow)
            if obs.enabled():
                # the sharded step computes kernel + digest in ONE
                # compiled program
                from ..obs import costmodel as _cm

                _cm.record_dispatch(
                    f"wave:sharded:{pipeline}:u{int(u_max)}",
                    site="wave")
        else:
            rank, visible, digest, overflow = dispatch_v5(lanes, u_max)
    n_retried = 0
    if overflow.any():
        # the token budget samples rows; a spiky unsampled row can
        # overflow. Retry just those rows (unsharded — a handful of
        # rows doesn't need the mesh) with a doubled budget before
        # resorting to host merges. np.array: jax host buffers can be
        # read-only.
        rows = np.flatnonzero(overflow)
        n_retried = len(rows)
        obs.counter("wave.overflow_retry").inc(len(rows))
        obs.event("wave.overflow_retry", rows=len(rows),
                  u_max=int(u_max))
        if obs.enabled():
            _recovery.step("wave", "full", "double_budget",
                           "token-overflow",
                           uuid=str(pairs[0][0].ct.uuid),
                           rows=n_retried)
        sub = {k: lanes[k][rows] for k in LANE_KEYS5}
        with obs.span("wave.dispatch.retry", rows=len(rows),
                      u_max=int(2 * u_max)):
            r2, v2, d2, ov2 = dispatch_v5(sub, 2 * u_max)
        rank = np.array(rank)
        visible = np.array(visible)
        digest = np.array(digest)
        overflow = np.array(overflow)
        rank[rows] = r2
        visible[rows] = v2
        digest[rows] = d2
        overflow[rows] = ov2

    B = len(pairs)
    full_rank = np.full((B, 2 * cap), 2 * cap, np.int32)
    full_vis = np.zeros((B, 2 * cap), bool)
    full_dig = np.zeros(B, np.uint32)
    dig_valid = np.zeros(B, bool)
    for j, i in enumerate(live):
        if bool(overflow[j]):
            a, b = pairs[i]
            if obs.enabled():
                # the ladder's last rung: still overflowing at the
                # doubled budget, this pair runs the host merge
                _recovery.step("wave", "double_budget", "host",
                               "token-overflow",
                               uuid=str(a.ct.uuid), pair=i)
            try:
                # budget blown: host path, correct
                fallback[i] = a.merge(b)
            except s.CausalError as err:  # corrupt AND overflowed
                err.info["pair"] = i
                poisoned[i] = err
            views[i] = None
            continue
        full_rank[i] = rank[j]
        full_vis[i] = visible[j]
        full_dig[i] = digest[j]
        dig_valid[i] = True
    obs.counter("wave.pairs").inc(B)
    obs.counter("wave.fallback").inc(len(fallback))
    obs.counter("wave.poisoned").inc(len(poisoned))
    if obs.enabled():
        # semantic layer: digest agreement, staleness aging, and (on
        # disagreement) one divergence event with site provenance
        sem = _observe_semantics(pairs, full_dig, dig_valid, "wave")
        # devprof wave-boundary sample: live device arrays + backend
        # memory after the dispatch settle, so per-wave residency
        # renders as a curve next to the dispatch spans
        from ..obs import devprof

        devprof.sample_device_memory("wave")
        # the cost-vs-divergence join: ONE wave.cost event carrying
        # this wave's dispatch count and program identities next to
        # its token work size (the O(delta) axis), its lane width
        # (the O(doc) axis) and the semantic digest summary
        from ..obs import costmodel as _cm

        # lanes/tokens are FLEET totals (lanes: the O(doc) transfer/
        # scan width; tokens: worst-row estimate × rows — the kernel
        # pads every row to the budget), same units as delta_ops
        _cm.wave_cost(uuid=str(pairs[0][0].ct.uuid), pairs=B,
                      lanes=2 * int(cap) * B,
                      tokens=int(u_need) * len(live_views),
                      token_budget=int(u_max) * len(live_views),
                      full_bag=len(fallback), poisoned=len(poisoned),
                      overflow_retries=n_retried, semantic=sem,
                      path="full")
    return WaveResult(pairs, views, cap, full_rank, full_vis, full_dig,
                      fallback, pipeline, dig_valid,
                      poisoned=poisoned)
