"""Multi-chip sharding of the batched weave/merge kernels
(mesh + shard_map + collectives)."""

from .mesh import (  # noqa: F401
    REPLICA_AXIS,
    make_mesh,
    replica_digest,
    sharded_merge_weave,
    sharded_merge_weave_v4,
    sharded_merge_weave_v5,
)
from . import recovery  # noqa: F401
from .session import FleetSession  # noqa: F401
from .tree import (  # noqa: F401
    flat_fold,
    merge_tree,
    merge_tree_report,
    tree_rounds,
)
from .wave import WaveResult, WaveBuffers, merge_wave  # noqa: F401
