"""Ordering and sorted-vector algorithms (reference: src/causal/util.cljc).

These operate on plain Python lists kept in sorted order; comparison is
native tuple comparison, which coincides with the reference's ``compare``
for the id / node / reverse-path shapes used throughout.
"""

from __future__ import annotations

__all__ = [
    "lt",
    "sorted_insertion_index",
    "insert_sorted",
    "binary_search",
    "char_seq",
]


def lt(a, b) -> bool:
    """``<<`` — strictly-increasing comparison (util.cljc:4-10)."""
    return a < b


def sorted_insertion_index(coll, target, uniq: bool = False):
    """Binary-search insertion index in an already-sorted list
    (util.cljc:25-39). With ``uniq=True`` returns None when an exactly
    equal element is already present (dedupe-on-insert)."""
    low, high = 0, len(coll) - 1
    while low <= high:
        mid = (low + high) // 2
        mid_val = coll[mid]
        if mid_val == target:
            return None if uniq else mid
        if mid_val < target:
            low = mid + 1
        else:
            high = mid - 1
    return low


def insert_sorted(coll, val, next_vals=None, index=None):
    """Splice ``val`` (and optionally a run of ``next_vals``) into a list.

    With ``index=None`` the list is assumed sorted and the sort is
    maintained; if an equal element already exists the list is returned
    unchanged (reference: util.cljc:41-48, the ``:uniq`` path).
    Always returns a new list.
    """
    if index is None:
        index = sorted_insertion_index(coll, val, uniq=True)
        if index is None:
            return list(coll)
    out = list(coll[:index])
    out.append(val)
    if next_vals:
        out.extend(next_vals)
    out.extend(coll[index:])
    return out


def char_seq(text: str):
    """Split a string into user-perceived character units
    (util.cljc:76-92).

    The reference exists to keep UTF-16 surrogate pairs together on the
    JVM/JS hosts; Python 3 strings are code-point sequences so astral
    chars are whole by construction. We additionally keep combining
    marks, ZWJ sequences and variation selectors glued to their base
    character — the case the reference documents as known-broken
    (util.cljc:94-97). Unlike the reference (whose char-seq is unused;
    base/core.cljc:146 falls back to seq), this IS the CausalBase
    flattener's string splitter (cbase.list_to_nodes), so a ZWJ emoji
    survives transact->edn as one node.
    """
    import unicodedata

    out = []
    cluster = ""
    join_next = False
    for ch in text:
        cp = ord(ch)
        is_zwj = cp == 0x200D
        is_extend = (
            unicodedata.combining(ch) != 0
            or 0xFE00 <= cp <= 0xFE0F      # variation selectors
            or 0x1F3FB <= cp <= 0x1F3FF    # emoji skin-tone modifiers
        )
        if cluster and (join_next or is_zwj or is_extend):
            cluster += ch
        else:
            if cluster:
                out.append(cluster)
            cluster = ch
        join_next = is_zwj
    if cluster:
        out.append(cluster)
    return out


def binary_search(xs, x, match_fn=None, less_than_fn=None):
    """Binary search a sorted list with custom match / less-than predicates
    (util.cljc:50-64). Returns a matching index or None."""
    if match_fn is None:
        match_fn = lambda v, t: v == t
    if less_than_fn is None:
        less_than_fn = lambda v, t: v < t
    left, right = 0, len(xs) - 1
    while left <= right:
        i = (left + right) // 2
        v = xs[i]
        if match_fn(v, x):
            return i
        if less_than_fn(v, x):
            left = i + 1
        else:
            right = i - 1
    return None
