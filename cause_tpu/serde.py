"""Serialization: tagged round-trip of causal collections and bases.

The reference checkpoints through data, not files: printed tagged
literals ``#causal/list`` / ``#causal/map`` / ``#causal/base`` round-trip
through the reader (reference: src/causal/collections/list.cljc:137-147,
map.cljc:218-228, base/core.cljc:424-432), and at rest only the
``nodes`` bag needs storing — caches are reconstituted with
``refresh-caches`` (shared.cljc:259-266, README.md:19).

cause_tpu keeps both properties with a JSON encoding:

- ``dumps``/``loads`` round-trip any CausalList / CausalMap /
  CausalBase (and plain EDN-ish values) through tagged JSON;
- only ``nodes`` is serialized per tree — ``loads`` rebuilds yarns and
  the weave with the tree's weave function, so a decoded tree is also a
  *proof* of cache idempotency;
- everything is plain text: ship it over any transport and the merge
  converges (the CRDT transport story, README.md:5).

Tag scheme (single-``~``-key JSON objects; plain scalars pass through):

====================  =========================================
``{"~k": name}``      Keyword
``{"~f": name}``      non-finite float (``nan`` / ``inf`` / ``-inf``)
``{"~s": name}``      Special (``hide`` / ``h.hide`` / ``h.show``)
``{"~r": uuid}``      Ref to a nested collection
``{"~t": [...]}``     tuple
``{"~set": [...]}``   set; ``{"~fset": [...]}`` frozenset
``{"~d": [[k,v]..]}`` dict (keys can be any encodable value)
``{"~causal": ...}``  CausalList / CausalMap / CausalBase
====================  =========================================

Node ids and id-valued causes are stored as plain ``[ts, site, tx]``
arrays: positionally unambiguous (map keys are hashable, so a raw
Python list can never be a key) and half the bytes of a tagged form.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from .cbase import CB, CausalBase, Ref
from .collections import ccounter as c_counter
from .collections import clist as c_list
from .collections import cmap as c_map
from .collections import cset as c_set
from .collections import shared as s
from .collections.ccounter import CausalCounter
from .collections.clist import CausalList
from .collections.cmap import CausalMap
from .collections.cset import CausalSet
from .collections.shared import CausalTree
from .ids import Keyword, Special, is_id

__all__ = [
    "to_data",
    "from_data",
    "dumps",
    "loads",
    "encode_node_items",
    "decode_node_items",
]

_INF = float("inf")


def _encode_id(nid) -> list:
    return [nid[0], nid[1], nid[2]]


def _encode_cause(cause):
    """A cause is an id (lists) or a key (maps). Ids go positional."""
    if is_id(cause):
        return _encode_id(cause)
    return to_data(cause)


def _decode_cause(d):
    if type(d) is list and len(d) == 3 and type(d[1]) is str:
        return (d[0], d[1], d[2])
    return from_data(d)


def encode_node_items(nodes_map: dict) -> list:
    """The on-wire node-triple encoding ``[id, cause, value]`` shared
    by tree checkpoints and sync frames — one definition so the two
    can never drift apart."""
    return [
        [_encode_id(nid), _encode_cause(cause), to_data(value)]
        for nid, (cause, value) in sorted(nodes_map.items())
    ]


def decode_node_items(data: list) -> dict:
    """Inverse of ``encode_node_items``."""
    out = {}
    for enc_id, enc_cause, enc_value in data:
        nid = (enc_id[0], enc_id[1], enc_id[2])
        out[nid] = (_decode_cause(enc_cause), from_data(enc_value))
    return out


def _encode_tree(ct: CausalTree) -> dict:
    nodes = encode_node_items(ct.nodes)
    return {
        "~causal": ct.type,
        "uuid": ct.uuid,
        "site_id": ct.site_id,
        "lamport_ts": ct.lamport_ts,
        "weaver": ct.weaver,
        "nodes": nodes,
    }


def _decode_tree(d: dict) -> CausalTree:
    """Reconstitute a tree from its bag of nodes: rebuild yarns, ts and
    the weave from scratch (refresh-caches parity, shared.cljc:259-266),
    then restore the recorded clock (it may run ahead of the max node
    ts, e.g. after tombstone-only activity elsewhere in a base)."""
    kind = d["~causal"]
    nodes = decode_node_items(d["nodes"])
    if kind == s.LIST_TYPE:
        fresh, weave_fn = c_list.new_causal_tree(d["weaver"]), c_list.weave
    elif kind == s.MAP_TYPE:
        fresh, weave_fn = c_map.new_causal_tree(d["weaver"]), c_map.weave
    elif kind == c_set.SET_TYPE:
        fresh, weave_fn = c_set.new_causal_tree(d["weaver"]), c_list.weave
    elif kind == c_counter.COUNTER_TYPE:
        fresh, weave_fn = (c_counter.new_causal_tree(d["weaver"]),
                           c_list.weave)
    else:
        raise s.CausalError("unknown causal tag", {"tag": kind})
    nodes.update(fresh.nodes)  # the seeded root sentinel (list trees)
    ct = fresh.evolve(uuid=d["uuid"], site_id=d["site_id"], nodes=nodes)
    ct = s.refresh_caches(weave_fn, ct)
    return ct.evolve(lamport_ts=max(ct.lamport_ts, d["lamport_ts"]))


def _encode_base(cb: CB) -> dict:
    return {
        "~causal": "base",
        "uuid": cb.uuid,
        "site_id": cb.site_id,
        "lamport_ts": cb.lamport_ts,
        "weaver": cb.weaver,
        "root_uuid": cb.root_uuid,
        "first_undo_lamport_ts": cb.first_undo_lamport_ts,
        "last_undo_lamport_ts": cb.last_undo_lamport_ts,
        "last_redo_lamport_ts": cb.last_redo_lamport_ts,
        "history": [[_encode_id(nid), uuid] for nid, uuid in cb.history],
        "collections": [to_data(c) for c in cb.collections.values()],
    }


def _decode_base(d: dict) -> CausalBase:
    collections = {}
    for enc in d["collections"]:
        coll = from_data(enc)
        collections[coll.get_uuid()] = coll
    cb = CB(
        lamport_ts=d["lamport_ts"],
        uuid=d["uuid"],
        site_id=d["site_id"],
        history=[((e[0][0], e[0][1], e[0][2]), e[1]) for e in d["history"]],
        first_undo_lamport_ts=d["first_undo_lamport_ts"],
        last_undo_lamport_ts=d["last_undo_lamport_ts"],
        last_redo_lamport_ts=d["last_redo_lamport_ts"],
        root_uuid=d["root_uuid"],
        collections=collections,
        weaver=d["weaver"],
    )
    return CausalBase(cb)


def to_data(x) -> Any:
    """Encode a value (causal or plain) to JSON-able tagged data.
    Non-finite floats get a tag (``{"~f": "nan"|"inf"|"-inf"}``) so the
    emitted JSON stays strict RFC 8259 — a bare NaN/Infinity literal
    would be rejected by every non-Python parser."""
    if isinstance(x, float) and x != x:
        return {"~f": "nan"}
    if isinstance(x, float) and (x == _INF or x == -_INF):
        return {"~f": "inf" if x > 0 else "-inf"}
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, Keyword):
        return {"~k": x.name}
    if isinstance(x, Special):
        return {"~s": x.name}
    if isinstance(x, Ref):
        return {"~r": x.uuid}
    if isinstance(x, (CausalList, CausalMap, CausalSet, CausalCounter)):
        return _encode_tree(x.ct)
    if isinstance(x, CausalTree):
        return _encode_tree(x)
    if isinstance(x, CausalBase):
        return _encode_base(x.cb)
    if isinstance(x, CB):
        return _encode_base(x)
    if isinstance(x, tuple):
        return {"~t": [to_data(v) for v in x]}
    if isinstance(x, frozenset):
        return {"~fset": sorted((to_data(v) for v in x), key=repr)}
    if isinstance(x, set):
        return {"~set": sorted((to_data(v) for v in x), key=repr)}
    if isinstance(x, dict):
        return {"~d": [[to_data(k), to_data(v)] for k, v in x.items()]}
    if isinstance(x, list):
        return [to_data(v) for v in x]
    raise s.CausalError(
        "value is not serializable", {"type": type(x).__name__}
    )


def from_data(d) -> Any:
    """Decode tagged data produced by ``to_data``. Decoded trees come
    back wrapped (CausalList / CausalMap), matching what the facade
    hands out."""
    if d is None or isinstance(d, (bool, int, float, str)):
        return d
    if isinstance(d, list):
        return [from_data(v) for v in d]
    if isinstance(d, dict):
        if "~f" in d:
            return {"nan": float("nan"), "inf": _INF, "-inf": -_INF}[d["~f"]]
        if "~k" in d:
            return Keyword(d["~k"])
        if "~s" in d:
            return Special(d["~s"])
        if "~r" in d:
            return Ref(d["~r"])
        if "~t" in d:
            return tuple(from_data(v) for v in d["~t"])
        if "~set" in d:
            return set(from_data(v) for v in d["~set"])
        if "~fset" in d:
            return frozenset(from_data(v) for v in d["~fset"])
        if "~d" in d:
            return {from_data(k): from_data(v) for k, v in d["~d"]}
        if "~causal" in d:
            if d["~causal"] == "base":
                return _decode_base(d)
            ct = _decode_tree(d)
            handle = {
                s.LIST_TYPE: CausalList,
                s.MAP_TYPE: CausalMap,
                c_set.SET_TYPE: CausalSet,
                c_counter.COUNTER_TYPE: CausalCounter,
            }[ct.type]
            return handle(ct)
    raise s.CausalError("undecodable data", {"data": type(d).__name__})


def dumps(x, indent: Optional[int] = None) -> str:
    """Serialize a causal collection / base / plain value to strict
    RFC-compliant JSON text (non-finite floats are tagged by
    ``to_data``, so ``allow_nan=False`` can never trip on them)."""
    return json.dumps(to_data(x), indent=indent, allow_nan=False)


def loads(text: str) -> Any:
    """Deserialize ``dumps`` output back to live causal values."""
    return from_data(json.loads(text))
