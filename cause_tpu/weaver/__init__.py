"""Weave backends.

- :mod:`cause_tpu.weaver.pure` — the host-side sequential scan, the
  semantics-defining default (reference: shared.cljc:194-241).
- :mod:`cause_tpu.weaver.jaxw` — the TPU device weaver: batched
  radix-sorted linearization + data-parallel visibility, vmap'd and
  shardable across replicas (the framework's north star). Imported
  lazily so host-only use never pays the JAX import.
- :mod:`cause_tpu.weaver.arrays` — host<->device marshalling (site-id
  interning, structure-of-arrays node buffers, id packing).

Selected per-tree via the ``weaver`` field ("pure" | "jax").
"""

from . import pure  # noqa: F401

BACKENDS = ("pure", "jax")
