"""Weave backends.

- :mod:`cause_tpu.weaver.pure` — the host-side sequential scan, the
  semantics-defining default (reference: shared.cljc:194-241).
- :mod:`cause_tpu.weaver.jaxw` — the TPU device weaver: batched
  radix-sorted linearization + data-parallel visibility, vmap'd and
  shardable across replicas (the framework's north star). Imported
  lazily so host-only use never pays the JAX import.
- :mod:`cause_tpu.weaver.arrays` — host<->device marshalling (site-id
  interning, structure-of-arrays node buffers, id packing).
- :mod:`cause_tpu.native` — the C++ host backend: O(n) reweaves and
  merges compiled on first use (falls back to pure if the toolchain
  is unavailable; see :func:`cause_tpu.native.available`).

Selected per-tree via the ``weaver`` field ("pure" | "native" | "jax").
"""

from . import pure  # noqa: F401

BACKENDS = ("pure", "native", "jax")
