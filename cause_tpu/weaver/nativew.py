"""The native host weave backend ("native"): full reweaves and merges
through the C++ linearizer (cause_tpu/native/weaver.cpp).

Same contract as the device weaver — the pure sequential weaver is the
oracle; this backend recomputes whole weaves in O(n) instead of the
O(n^2) host replay (reference: src/causal/collections/list.cljc:20-28)
and turns merges into union + one reweave instead of the O(n*m)
reduce-insert (shared.cljc:300-314). Incremental single-node weaves
stay on the pure path, where the O(n) scan is already optimal.

Fallback discipline: any input outside the native domain (a weft-cut
"gibberish tree" with dangling causes, a map whose id-caused nodes
target other id-caused nodes — semantics the pure weaver defines by
its insertion scan, not by tree structure) silently falls back to the
pure full rebuild, so ``weaver="native"`` never changes semantics, only
speed.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .. import native
from .arrays import OutsideDomain as _OutsideDomain

__all__ = [
    "available",
    "refresh_list_weave",
    "refresh_map_weave",
    "merge_trees",
]


def available() -> bool:
    return native.available()


def _list_lanes(nodes_map) -> Tuple[list, np.ndarray, np.ndarray]:
    """(sorted_nodes, cause_idx, vclass) for a list tree, via the shared
    NodeArrays marshaller (lane order = sorted id order, lane 0 = root).
    A dangling cause (weft gibberish) is outside the native domain."""
    from .arrays import NodeArrays

    na = NodeArrays.from_nodes_map(nodes_map, capacity=max(1, len(nodes_map)))
    n = na.n
    if n > 1 and (na.cause_idx[1:n] < 0).any():
        raise _OutsideDomain()
    return na.nodes, na.cause_idx[:n], na.vclass[:n]


def _inverse_permutation(rank: np.ndarray) -> np.ndarray:
    """rank is a bijection of 0..n-1; its inverse in O(n)."""
    order = np.empty(rank.shape[0], np.intp)
    order[rank] = np.arange(rank.shape[0], dtype=np.intp)
    return order


def refresh_list_weave(ct):
    """Full list-weave rebuild through the native linearizer; identical
    output to the pure replay (falls back to it off-domain). Reuses —
    and attaches — the persistent lane cache when the tree is inside
    its domain, so native trees share the incremental-marshal benefits
    (PackSpec-overflowing ids keep the direct marshal: the native
    linearizer needs no packed lanes)."""
    from ..collections import clist as c_list
    from . import lanecache

    # PackSpec-overflowing trees (view None) re-marshal via
    # _list_lanes — a second O(n) pass, accepted: the native linearizer
    # works beyond the packed-id domain and such trees are rare corners
    view = lanecache.view_for(ct)
    try:
        if view is not None:
            a, n = view.arena, view.n
            nodes = a.nodes[:n]
            cause_idx = a.cause_idx[:n]
            vclass = a.vclass[:n]
            if n > 1 and (cause_idx[1:] < 0).any():
                raise _OutsideDomain()  # dangling causes (weft gibberish)
        else:
            nodes, cause_idx, vclass = _list_lanes(ct.nodes)
        rank = native.weave_list_ranks(cause_idx, vclass)
    except (RuntimeError, _OutsideDomain):
        return c_list.weave(ct.evolve(weaver="pure")).evolve(weaver=ct.weaver)
    order = _inverse_permutation(rank)
    return ct.evolve(weave=[nodes[i] for i in order], lanes=view)


def refresh_map_weave(ct):
    """Full map-weave rebuild through the native linearizer: one forest
    preorder, split into the per-key weave dict (identical to the pure
    per-key replay; falls back off-domain)."""
    from ..collections import cmap as c_map

    from .arrays import map_lanes, rebuild_map_weave

    try:
        nodes, cause_idx, key_rank, vclass, keys = map_lanes(ct.nodes)
        rank, key_out = native.weave_map_ranks(
            cause_idx, key_rank, vclass, len(keys)
        )
    except (RuntimeError, _OutsideDomain):
        return c_map.weave(ct.evolve(weaver="pure")).evolve(weaver=ct.weaver)
    order = _inverse_permutation(rank)
    return ct.evolve(weave=rebuild_map_weave(nodes, key_out, order, keys))


def refresh_weave(ct):
    from ..collections import shared as s

    # only map trees carry the per-key weave dict; every other type
    # (list, and the list-shaped set/counter) uses the flat list weave
    if ct.type == s.MAP_TYPE:
        return refresh_map_weave(ct)
    return refresh_list_weave(ct)


def merge_trees(ct1, ct2):
    """Union the node stores host-side, then one native reweave —
    O(n+m) instead of the reference's O(n*m) reduce-insert, with an
    identical resulting tree."""
    from ..collections import shared as s

    return refresh_weave(s.union_nodes(ct1, ct2))
