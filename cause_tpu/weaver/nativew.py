"""The native host weave backend ("native"): full reweaves and merges
through the C++ linearizer (cause_tpu/native/weaver.cpp).

Same contract as the device weaver — the pure sequential weaver is the
oracle; this backend recomputes whole weaves in O(n) instead of the
O(n^2) host replay (reference: src/causal/collections/list.cljc:20-28)
and turns merges into union + one reweave instead of the O(n*m)
reduce-insert (shared.cljc:300-314). Incremental single-node weaves
stay on the pure path, where the O(n) scan is already optimal.

Fallback discipline: any input outside the native domain (a weft-cut
"gibberish tree" with dangling causes, a map whose id-caused nodes
target other id-caused nodes — semantics the pure weaver defines by
its insertion scan, not by tree structure) silently falls back to the
pure full rebuild, so ``weaver="native"`` never changes semantics, only
speed.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from .. import native
from ..ids import ROOT_ID, ROOT_NODE, is_id

__all__ = [
    "available",
    "refresh_list_weave",
    "refresh_map_weave",
    "merge_trees",
]


def available() -> bool:
    return native.available()


class _OutsideDomain(Exception):
    pass


def _list_lanes(nodes_map) -> Tuple[list, np.ndarray, np.ndarray]:
    """(sorted_nodes, cause_idx, vclass) for a list tree, via the shared
    NodeArrays marshaller (lane order = sorted id order, lane 0 = root).
    A dangling cause (weft gibberish) is outside the native domain."""
    from .arrays import NodeArrays

    na = NodeArrays.from_nodes_map(nodes_map, capacity=max(1, len(nodes_map)))
    n = na.n
    if n > 1 and (na.cause_idx[1:n] < 0).any():
        raise _OutsideDomain()
    return na.nodes, na.cause_idx[:n], na.vclass[:n]


def _inverse_permutation(rank: np.ndarray) -> np.ndarray:
    """rank is a bijection of 0..n-1; its inverse in O(n)."""
    order = np.empty(rank.shape[0], np.intp)
    order[rank] = np.arange(rank.shape[0], dtype=np.intp)
    return order


def refresh_list_weave(ct):
    """Full list-weave rebuild through the native linearizer; identical
    output to the pure replay (falls back to it off-domain)."""
    from ..collections import clist as c_list

    try:
        nodes, cause_idx, vclass = _list_lanes(ct.nodes)
        rank = native.weave_list_ranks(cause_idx, vclass)
    except (RuntimeError, _OutsideDomain):
        return c_list.weave(ct.evolve(weaver="pure")).evolve(weaver=ct.weaver)
    order = _inverse_permutation(rank)
    return ct.evolve(weave=[nodes[i] for i in order])


def _map_lanes(nodes_map):
    """(sorted_nodes, cause_idx, key_rank, vclass, keys) for a map tree.

    Key resolution follows the pure weaver exactly (single level:
    an id-caused node's key is its target's cause, map.cljc:31-37), so
    the native domain requires id-caused nodes to target key-caused
    nodes — everything the collection/base APIs generate.
    """
    from .arrays import vclass_of

    ids = sorted(nodes_map)
    idx_of = {nid: i for i, nid in enumerate(ids)}
    n = len(ids)
    cause_idx = np.full(n, -1, np.int32)
    key_rank = np.full(n, -1, np.int32)
    vclass = np.zeros(n, np.int32)
    keys: List = []
    key_ordinal: Dict = {}
    nodes = []
    for i, nid in enumerate(ids):
        cause, value = nodes_map[nid]
        vclass[i] = vclass_of(value)
        if is_id(cause):
            ci = idx_of.get(tuple(cause), -1)
            if ci < 0:
                raise _OutsideDomain()  # dangling target
            target_cause = nodes_map[tuple(cause)][0]
            if is_id(target_cause):
                raise _OutsideDomain()  # id-caused targeting id-caused
            cause_idx[i] = ci
        else:
            k = cause
            if k not in key_ordinal:
                key_ordinal[k] = len(keys)
                keys.append(k)
            key_rank[i] = key_ordinal[k]
        nodes.append((nid, cause, value))
    return nodes, cause_idx, key_rank, vclass, keys


def refresh_map_weave(ct):
    """Full map-weave rebuild through the native linearizer: one forest
    preorder, split into the per-key weave dict (identical to the pure
    per-key replay; falls back off-domain)."""
    from ..collections import cmap as c_map

    try:
        nodes, cause_idx, key_rank, vclass, keys = _map_lanes(ct.nodes)
        rank, key_out = native.weave_map_ranks(
            cause_idx, key_rank, vclass, len(keys)
        )
    except (RuntimeError, _OutsideDomain):
        return c_map.weave(ct.evolve(weaver="pure")).evolve(weaver=ct.weaver)
    order = _inverse_permutation(rank)
    weave: Dict = {}
    for i in order:
        nid, cause, value = nodes[i]
        k = keys[key_out[i]]
        in_weave_cause = cause if is_id(cause) else ROOT_ID
        weave.setdefault(k, [ROOT_NODE]).append((nid, in_weave_cause, value))
    return ct.evolve(weave=weave)


def refresh_weave(ct):
    from ..collections import shared as s

    if ct.type == s.LIST_TYPE:
        return refresh_list_weave(ct)
    return refresh_map_weave(ct)


def merge_trees(ct1, ct2):
    """Union the node stores host-side, then one native reweave —
    O(n+m) instead of the reference's O(n*m) reduce-insert, with an
    identical resulting tree."""
    from ..collections import shared as s

    return refresh_weave(s.union_nodes(ct1, ct2))
