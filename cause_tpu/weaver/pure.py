"""The pure host weaver: the semantics-defining sequential weave kernel.

This is the port-of-record of the reference's conflict-resolution core
(reference: src/causal/collections/shared.cljc:194-241): ``weave_asap``
and ``weave_later`` are the two sibling-ordering predicates, and
``weave_node`` is the insertion scan that places one node (plus an
optional run of consecutive same-transaction nodes) into an existing
weave. It is used as

1. the default backend for incremental single-node / single-tx weaving
   (cheap, O(n) per insert), and
2. the differential-test oracle for the JAX device weaver
   (cause_tpu.weaver.jaxw), which recomputes whole weaves in parallel
   and must agree with this scan node-for-node.

Semantics notes (derived, and fuzz-verified against the reference's own
regression corpus):

* The woven order is a preorder DFS of the causal tree where the
  children of each node are ordered specials-first, then by descending
  id; among specials also descending id. ``weave_later``'s second
  disjunct (shared.cljc:213-219) is logically subsumed by its third
  (shared.cljc:220-223), so the ``seen`` set never changes the result;
  it is kept here for exactness.
* A special node always sticks immediately after the node it targets
  (its cause); a non-special sibling can never cut in front of it
  (the first ``weave_later`` disjunct, shared.cljc:208-212).
"""

from __future__ import annotations

from ..ids import is_special

__all__ = ["weave_asap", "weave_later", "weave_node"]


def weave_asap(nl, nm, nr) -> bool:
    """Should ``nm`` be inserted as soon as possible between ``nl``/``nr``?
    (shared.cljc:194-200). True once the scan has just passed ``nm``'s
    cause, or when ``nr`` is caused by ``nm``."""
    return (nl is not None and nl[0] == nm[1]) or (
        nr is not None and nm[0] == nr[1]
    )


def weave_later(nl, nm, nr, seen) -> bool:
    """Is there a reason ``nm`` cannot go between ``nl`` and ``nr``?
    (shared.cljc:202-223). Assumes weave_asap already holds."""
    nm_special = is_special(nm[2])
    nr_special = is_special(nr[2])
    # 1) nr is a hide/show that does not target nm: it must stay glued to
    #    its own target, unless nm is a *newer* special.
    if (
        nr_special
        and nm[0] != nr[1]
        and (not nm_special or nm[0] < nr[0])
    ):
        return True
    # 2) nr starts a sibling subtree (caused by nl, shares a cause with
    #    nl, or caused by an already-seen node) and nm is older: wait.
    #    (Subsumed by 3; kept for exactness with the reference.)
    if (
        (
            (nl is not None and (nl[0] == nr[1] or nl[1] == nr[1]))
            or nr[1] in seen
        )
        and nm[0] < nr[0]
        and (not nm_special or nr_special)
    ):
        return True
    # 3) nm is older than nr (and not a special jumping a non-special):
    #    newer siblings and their subtrees come first.
    if nm[0] < nr[0] and (not nm_special or nr_special):
        return True
    return False


def weave_node(current_weave, node, more_consecutive_nodes_in_same_tx=None):
    """Return a new list-weave with ``node`` (and an optional contiguous
    same-transaction run) woven in (shared.cljc:225-241).

    O(n) scan: walk the weave left to right; once ``weave_asap`` fires,
    insert at the first position ``weave_later`` does not veto. A run of
    m consecutive tx nodes is spliced in one pass, keeping transactional
    pastes O(n+m) rather than O(n*m) (reference: list.cljc:23-25).
    """
    w = current_weave
    n = len(w)
    prev_asap = False
    seen = set()
    i = 0
    nl = None
    while True:
        nr = w[i] if i < n else None
        asap = prev_asap or weave_asap(nl, node, nr)
        if nr is None or (asap and not weave_later(nl, node, nr, seen)):
            out = list(w[:i])
            out.append(node)
            if more_consecutive_nodes_in_same_tx:
                out.extend(more_consecutive_nodes_in_same_tx)
            out.extend(w[i:])
            return out
        if asap:
            # the reference conjes (first nl) — None before any step
            seen.add(nl[0] if nl is not None else None)
        nl = nr
        i += 1
        prev_asap = asap
