"""Host <-> device marshalling for the JAX weaver.

The device never sees values, site-id strings, or Python objects — only
fixed-width integer lanes (the "ids and classes only" contract from the
build plan, SURVEY.md §7):

- ``ts``, ``site``, ``tx`` (int32): the node id triple with site-id
  strings interned to **order-preserving** integer ranks, so
  lexicographic (ts, site_rank, tx) order equals the host id order.
  Ranks must be computed over the union of sites in play (all trees of
  a merge/batch) or cross-replica comparisons would disagree.
- ``cause_idx`` (int32): index of the cause node in the same array
  (-1 for the root and for key-caused map nodes).
- ``vclass`` (int32): 0 normal, 1 hide, 2 h.hide, 3 h.show
  (the special values of shared.cljc:21).
- ``valid`` (bool): padding mask — trees grow, TPU shapes don't.

Node ids also pack into a two-lane **(hi, lo) int32 pair**
(``PackSpec``: hi = ts, lo = site_rank<<tx_bits | tx) for duplicate
elimination and sort-join cause resolution in the batched merge kernel.
Two int32 lanes, not one int64: JAX under default (non-x64) config
silently downcasts int64, and TPUs prefer 32-bit lanes anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..ids import HIDE, H_HIDE, H_SHOW, ROOT_ID, is_special

__all__ = [
    "VCLASS_NORMAL",
    "VCLASS_HIDE",
    "VCLASS_H_HIDE",
    "VCLASS_H_SHOW",
    "PackSpec",
    "DEFAULT_PACK",
    "SiteInterner",
    "NodeArrays",
    "OutsideDomain",
    "map_lanes",
    "rebuild_map_weave",
    "vclass_of",
    "next_pow2",
]


class OutsideDomain(Exception):
    """The input is outside an accelerated weaver's domain (dangling
    causes from weft gibberish, exotic map cause chains); callers fall
    back to the pure weaver, which defines the semantics everywhere."""

VCLASS_NORMAL = 0
VCLASS_HIDE = 1
VCLASS_H_HIDE = 2
VCLASS_H_SHOW = 3


def vclass_of(value) -> int:
    if value is HIDE:
        return VCLASS_HIDE
    if value is H_HIDE:
        return VCLASS_H_HIDE
    if value is H_SHOW:
        return VCLASS_H_SHOW
    return VCLASS_NORMAL


def next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p <<= 1
    return p


@dataclass(frozen=True)
class PackSpec:
    """Bit layout for the (hi, lo) id lanes: ``hi = ts`` (int32) and
    ``lo = (site_rank << tx_bits) | tx`` (int32). Defaults allow
    ts < 2^31-1, < 2^18 sites, tx < 2^13 (31 bits in lo); ``check``
    raises before any silent wraparound and reserves the all-ones
    packings for the I32_MAX padding sentinel. Lexicographic (hi, lo)
    order equals id order."""

    site_bits: int = 18
    tx_bits: int = 13

    def check(self, max_ts: int, n_sites: int, max_tx: int) -> None:
        # strict: the all-ones packings are reserved for the I32_MAX
        # padding sentinel, so a maximal real id must never reach them
        if max_ts >= (1 << 31) - 1:
            raise OverflowError(f"lamport-ts {max_ts} reaches the padding sentinel")
        if n_sites >= (1 << self.site_bits):
            raise OverflowError(f"{n_sites} sites exceed {self.site_bits} bits")
        if max_tx >= (1 << self.tx_bits):
            raise OverflowError(f"tx-index {max_tx} exceeds {self.tx_bits} bits")

    def pack_lo(self, site, tx):
        """Works on numpy arrays or jax arrays (pure int32 arithmetic)."""
        return (site.astype(np.int32) << self.tx_bits) | tx.astype(np.int32)


DEFAULT_PACK = PackSpec()

I32_MAX = np.int32(np.iinfo(np.int32).max)


class SiteInterner:
    """Order-preserving site-id -> rank mapping over a fixed site set.

    Built from the union of every site involved in a kernel invocation;
    sorted-string order defines the ranks, so integer comparisons on
    ranks agree with the host's lexicographic id order (SURVEY.md §7
    hard part 3)."""

    def __init__(self, sites):
        self.sites: List[str] = sorted(set(sites))
        self.rank: Dict[str, int] = {s: i for i, s in enumerate(self.sites)}

    def __len__(self) -> int:
        return len(self.sites)

    def __getitem__(self, site: str) -> int:
        return self.rank[site]


@dataclass
class NodeArrays:
    """Structure-of-arrays view of one causal tree's nodes, padded to
    ``capacity``. ``nodes[i]`` is the host node triple for lane i; the
    root sentinel is always lane 0 (ids sort it first)."""

    ts: np.ndarray
    site: np.ndarray
    tx: np.ndarray
    cause_idx: np.ndarray
    vclass: np.ndarray
    valid: np.ndarray
    nodes: list
    interner: SiteInterner
    n: int

    @property
    def capacity(self) -> int:
        return int(self.ts.shape[0])

    @classmethod
    def from_nodes_map(
        cls,
        nodes_map: dict,
        capacity: Optional[int] = None,
        interner: Optional[SiteInterner] = None,
    ) -> "NodeArrays":
        """Build device lanes from a ``{id: (cause, value)}`` store.
        Lanes are in sorted id order (so lane index order == id order
        and every cause precedes its effects)."""
        ids = sorted(nodes_map)
        n = len(ids)
        cap = capacity or next_pow2(n)
        if cap < n:
            raise ValueError(f"capacity {cap} < node count {n}")
        if interner is None:
            interner = SiteInterner(i[1] for i in ids)
        idx_of = {nid: i for i, nid in enumerate(ids)}
        ts = np.zeros(cap, np.int32)
        site = np.zeros(cap, np.int32)
        tx = np.zeros(cap, np.int32)
        cause_idx = np.full(cap, -1, np.int32)
        vclass = np.zeros(cap, np.int32)
        valid = np.zeros(cap, bool)
        nodes = []
        for i, nid in enumerate(ids):
            cause, value = nodes_map[nid]
            ts[i], site[i], tx[i] = nid[0], interner[nid[1]], nid[2]
            ci = idx_of.get(cause, -1) if isinstance(cause, tuple) else -1
            cause_idx[i] = ci
            vclass[i] = vclass_of(value)
            valid[i] = True
            nodes.append((nid, cause, value))
        return cls(
            ts=ts, site=site, tx=tx, cause_idx=cause_idx, vclass=vclass,
            valid=valid, nodes=nodes, interner=interner, n=n,
        )

    def id_lanes(self, spec: PackSpec = DEFAULT_PACK):
        """(hi, lo) int32 id lanes; padding lanes get int32 max so they
        sort last (real ids never reach int32 max by ``check``)."""
        max_ts = int(self.ts[: self.n].max(initial=0))
        max_tx = int(self.tx[: self.n].max(initial=0))
        spec.check(max_ts, len(self.interner), max_tx)
        hi = np.where(self.valid, self.ts.astype(np.int32), I32_MAX)
        lo = np.where(self.valid, spec.pack_lo(self.site, self.tx), I32_MAX)
        return hi, lo

    def cause_lanes(self, spec: PackSpec = DEFAULT_PACK):
        """(hi, lo) lanes of each node's cause id, or (-1, -1) when the
        cause is not an id (root sentinel, key causes, padding)."""
        from ..ids import is_id

        hi = np.full(self.capacity, -1, np.int32)
        lo = np.full(self.capacity, -1, np.int32)
        for i in range(self.n):
            cause = self.nodes[i][1]
            # any id-shaped cause, even one living in another replica's
            # tree (merges resolve causes against the union)
            if is_id(cause):
                hi[i] = cause[0]
                lo[i] = int(spec.pack_lo(np.int32(self.interner[cause[1]]),
                                         np.int32(cause[2])))
        return hi, lo


def map_lanes(nodes_map):
    """``(sorted_nodes, cause_idx, key_rank, vclass, keys)`` for a map
    tree — the shared marshaller of the native and device map weavers.

    Key resolution follows the pure weaver exactly (single level: an
    id-caused node's key is its target's cause, map.cljc:31-37), so the
    accelerated domain requires id-caused nodes to target key-caused
    nodes — everything the collection/base APIs generate. Anything else
    raises ``OutsideDomain`` and the caller falls back to pure.
    """
    from ..ids import is_id

    ids = sorted(nodes_map)
    idx_of = {nid: i for i, nid in enumerate(ids)}
    n = len(ids)
    cause_idx = np.full(n, -1, np.int32)
    key_rank = np.full(n, -1, np.int32)
    vclass = np.zeros(n, np.int32)
    keys = []
    key_ordinal = {}
    nodes = []
    for i, nid in enumerate(ids):
        cause, value = nodes_map[nid]
        vclass[i] = vclass_of(value)
        if is_id(cause):
            ci = idx_of.get(tuple(cause), -1)
            if ci < 0:
                raise OutsideDomain()  # dangling target
            target_cause = nodes_map[tuple(cause)][0]
            if is_id(target_cause):
                raise OutsideDomain()  # id-caused targeting id-caused
            cause_idx[i] = ci
        else:
            k = cause
            if k not in key_ordinal:
                key_ordinal[k] = len(keys)
                keys.append(k)
            key_rank[i] = key_ordinal[k]
        nodes.append((nid, cause, value))
    return nodes, cause_idx, key_rank, vclass, keys


def rebuild_map_weave(nodes, key_of, order, keys):
    """Split an accelerated forest ordering back into the per-key weave
    dict — shared by the native and device map weavers. ``nodes`` are
    host triples in lane order, ``key_of[i]`` each lane's resolved key
    ordinal, ``order`` the lanes in global weave order. Key-caused
    nodes' in-weave cause is rewritten to the root sentinel
    (map.cljc:77)."""
    from ..ids import ROOT_ID, ROOT_NODE, is_id

    weave = {}
    for i in order:
        nid, cause, value = nodes[i]
        k = keys[key_of[i]]
        in_weave_cause = cause if is_id(cause) else ROOT_ID
        weave.setdefault(k, [ROOT_NODE]).append((nid, in_weave_cause, value))
    return weave
