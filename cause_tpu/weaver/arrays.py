"""Host <-> device marshalling for the JAX weaver.

The device never sees values, site-id strings, or Python objects — only
fixed-width integer lanes (the "ids and classes only" contract from the
build plan, SURVEY.md §7):

- ``ts``, ``site``, ``tx`` (int32): the node id triple with site-id
  strings interned to **order-preserving** integer ranks, so
  lexicographic (ts, site_rank, tx) order equals the host id order.
  Ranks must be computed over the union of sites in play (all trees of
  a merge/batch) or cross-replica comparisons would disagree.
- ``cause_idx`` (int32): index of the cause node in the same array
  (-1 for the root and for key-caused map nodes).
- ``vclass`` (int32): 0 normal, 1 hide, 2 h.hide, 3 h.show
  (the special values of shared.cljc:21).
- ``valid`` (bool): padding mask — trees grow, TPU shapes don't.

Node ids also pack into a two-lane **(hi, lo) int32 pair**
(``PackSpec``: hi = ts, lo = site_rank<<tx_bits | tx) for duplicate
elimination and sort-join cause resolution in the batched merge kernel.
Two int32 lanes, not one int64: JAX under default (non-x64) config
silently downcasts int64, and TPUs prefer 32-bit lanes anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..ids import HIDE, H_HIDE, H_SHOW, ROOT_ID, is_special

__all__ = [
    "VCLASS_NORMAL",
    "VCLASS_HIDE",
    "VCLASS_H_HIDE",
    "VCLASS_H_SHOW",
    "PackSpec",
    "DEFAULT_PACK",
    "SiteInterner",
    "NodeArrays",
    "OutsideDomain",
    "map_lanes",
    "rebuild_map_weave",
    "vclass_of",
    "next_pow2",
]


class OutsideDomain(Exception):
    """The input is outside an accelerated weaver's domain (dangling
    causes from weft gibberish, exotic map cause chains); callers fall
    back to the pure weaver, which defines the semantics everywhere."""

VCLASS_NORMAL = 0
VCLASS_HIDE = 1
VCLASS_H_HIDE = 2
VCLASS_H_SHOW = 3


def vclass_of(value) -> int:
    if value is HIDE:
        return VCLASS_HIDE
    if value is H_HIDE:
        return VCLASS_H_HIDE
    if value is H_SHOW:
        return VCLASS_H_SHOW
    return VCLASS_NORMAL


def next_pow2(n: int) -> int:
    p = 8
    while p < n:
        p <<= 1
    return p


@dataclass(frozen=True)
class PackSpec:
    """Bit layout for the (hi, lo) id lanes: ``hi = ts`` (int32) and
    ``lo = (site_rank << tx_bits) | tx`` (int32). Defaults allow
    ts < 2^31-1, < 2^18 sites, tx < 2^13 (31 bits in lo); ``check``
    raises before any silent wraparound and reserves the all-ones
    packings for the I32_MAX padding sentinel. Lexicographic (hi, lo)
    order equals id order."""

    site_bits: int = 18
    tx_bits: int = 13

    def check(self, max_ts: int, n_sites: int, max_tx: int) -> None:
        # strict: the all-ones packings are reserved for the I32_MAX
        # padding sentinel, so a maximal real id must never reach them
        if max_ts >= (1 << 31) - 1:
            raise OverflowError(f"lamport-ts {max_ts} reaches the padding sentinel")
        if n_sites >= (1 << self.site_bits):
            raise OverflowError(f"{n_sites} sites exceed {self.site_bits} bits")
        if max_tx >= (1 << self.tx_bits):
            raise OverflowError(f"tx-index {max_tx} exceeds {self.tx_bits} bits")

    def pack_lo(self, site, tx):
        """Works on numpy arrays or jax arrays (pure int32 arithmetic)."""
        return (site.astype(np.int32) << self.tx_bits) | tx.astype(np.int32)


DEFAULT_PACK = PackSpec()

I32_MAX = np.int32(np.iinfo(np.int32).max)


class SiteInterner:
    """Order-preserving site-id -> rank mapping over a fixed site set.

    Built from the union of every site involved in a kernel invocation;
    sorted-string order defines the ranks, so integer comparisons on
    ranks agree with the host's lexicographic id order (SURVEY.md §7
    hard part 3)."""

    def __init__(self, sites):
        self.sites: List[str] = sorted(set(sites))
        self.rank: Dict[str, int] = {s: i for i, s in enumerate(self.sites)}

    def __len__(self) -> int:
        return len(self.sites)

    def __getitem__(self, site: str) -> int:
        return self.rank[site]


@dataclass
class NodeArrays:
    """Structure-of-arrays view of one causal tree's nodes, padded to
    ``capacity``. ``nodes[i]`` is the host node triple for lane i; the
    root sentinel is always lane 0 (ids sort it first)."""

    ts: np.ndarray
    site: np.ndarray
    tx: np.ndarray
    cause_idx: np.ndarray
    vclass: np.ndarray
    valid: np.ndarray
    cause_hi: np.ndarray
    cause_lo: np.ndarray
    nodes: list
    interner: SiteInterner
    n: int
    # the PackSpec the (cause_)hi/lo lanes were built with, and whether
    # the ids actually fit it (False = host-only marshal: cause_idx is
    # dict-resolved, device lanes raise)
    spec: PackSpec = DEFAULT_PACK
    spec_ok: bool = True

    @property
    def capacity(self) -> int:
        return int(self.ts.shape[0])

    @classmethod
    def from_nodes_map(
        cls,
        nodes_map: dict,
        capacity: Optional[int] = None,
        interner: Optional[SiteInterner] = None,
        spec: PackSpec = DEFAULT_PACK,
    ) -> "NodeArrays":
        """Build device lanes from a ``{id: (cause, value)}`` store.
        Lanes are in sorted id order (so lane index order == id order
        and every cause precedes its effects). Column extraction is a
        handful of comprehensions; cause resolution is one vectorized
        searchsorted over packed 64-bit id keys — the 10k-node API-level
        marshal is numpy-bound, not Python-loop-bound."""
        from ..ids import is_id

        ids = sorted(nodes_map)
        n = len(ids)
        cap = capacity or next_pow2(n)
        if cap < n:
            raise ValueError(f"capacity {cap} < node count {n}")
        if interner is None:
            interner = SiteInterner(i[1] for i in ids)
        bodies = [nodes_map[nid] for nid in ids]
        nodes = [(nid, c, v) for nid, (c, v) in zip(ids, bodies)]

        ts = np.zeros(cap, np.int32)
        site = np.zeros(cap, np.int32)
        tx = np.zeros(cap, np.int32)
        vclass = np.zeros(cap, np.int32)
        valid = np.zeros(cap, bool)
        cause_idx = np.full(cap, -1, np.int32)
        cause_hi = np.full(cap, -1, np.int32)
        cause_lo = np.full(cap, -1, np.int32)
        if n:
            # dict lookups beat numpy unicode arrays for site interning
            # (and raise KeyError on a site missing from a shared
            # interner, which a searchsorted would silently mis-rank)
            rank = interner.rank
            ts[:n] = np.fromiter((i[0] for i in ids), np.int64, n)
            site[:n] = np.fromiter((rank[i[1]] for i in ids), np.int64, n)
            tx[:n] = np.fromiter((i[2] for i in ids), np.int64, n)
            vclass[:n] = np.fromiter(
                (vclass_of(v) for _, v in bodies), np.int32, n
            )
            valid[:n] = True

            causes = [c if is_id(c) else None for c, _ in bodies]
            has_cause = np.fromiter(
                (c is not None for c in causes), bool, n
            )
            c_tx_max = 0
            if has_cause.any():
                c_tx_max = max(c[2] for c in causes if c)
            max_tx_all = int(max(int(tx[:n].max(initial=0)), c_tx_max))
            try:
                spec.check(int(ts[:n].max(initial=0)), len(interner),
                           max_tx_all)
                spec_ok = True
            except OverflowError:
                # the host-only backends (nativew) need no (hi, lo)
                # packing; resolve causes by dict instead and leave the
                # device lanes unusable (id_lanes/cause_lanes re-check)
                spec_ok = False
            if has_cause.any() and spec_ok:
                c_ts = np.fromiter(
                    (c[0] if c else 0 for c in causes), np.int64, n
                )
                # a cause site unknown to the interner can never match a
                # lane, so it gets the one-past-the-end rank: the packed
                # query misses and the cause resolves to -1 (dangling)
                ghost = len(interner)
                c_site = np.fromiter(
                    (rank.get(c[1], ghost) if c else 0 for c in causes),
                    np.int64, n,
                )
                c_tx = np.fromiter(
                    (c[2] if c else 0 for c in causes), np.int64, n
                )
                chi = c_ts.astype(np.int32)
                clo = (c_site.astype(np.int32) << spec.tx_bits) | c_tx.astype(
                    np.int32
                )
                cause_hi[:n] = np.where(has_cause, chi, -1)
                cause_lo[:n] = np.where(has_cause, clo, -1)
                # resolve cause -> lane via packed keys (ids sorted =>
                # packed keys sorted, given the spec bounds hold)
                key = (ts[:n].astype(np.int64) << 32) | (
                    spec.pack_lo(site[:n], tx[:n]).astype(np.int64)
                    & 0xFFFFFFFF
                )
                q = (chi.astype(np.int64) << 32) | (
                    clo.astype(np.int64) & 0xFFFFFFFF
                )
                pos = np.searchsorted(key, q)
                pos_c = np.clip(pos, 0, n - 1)
                found = has_cause & (key[pos_c] == q)
                cause_idx[:n] = np.where(found, pos_c, -1)
            elif has_cause.any():
                idx_of = {nid: i for i, nid in enumerate(ids)}
                cause_idx[:n] = np.fromiter(
                    (idx_of.get(c, -1) if c else -1 for c in causes),
                    np.int64, n,
                )
        else:
            spec_ok = True
        return cls(
            ts=ts, site=site, tx=tx, cause_idx=cause_idx, vclass=vclass,
            valid=valid, cause_hi=cause_hi, cause_lo=cause_lo, nodes=nodes,
            interner=interner, n=n, spec=spec, spec_ok=spec_ok,
        )

    def id_lanes(self, spec: Optional[PackSpec] = None):
        """(hi, lo) int32 id lanes; padding lanes get int32 max so they
        sort last (real ids never reach int32 max by ``check``). The
        layout is fixed at marshal time — a different spec requires a
        re-marshal (so id and cause lanes can never disagree)."""
        if spec is not None and spec != self.spec:
            raise ValueError(
                "id_lanes are packed with the from_nodes_map spec "
                f"{self.spec}; re-marshal to use {spec}"
            )
        if not self.spec_ok:
            # covers cause-id overflow too (a node-only re-check would
            # let an overflowed cause slip through as silently dangling)
            raise OverflowError(
                "ids exceed the PackSpec bit layout; device lanes are "
                "unavailable (host backends can still use cause_idx)"
            )
        spec = self.spec
        max_ts = int(self.ts[: self.n].max(initial=0))
        max_tx = int(self.tx[: self.n].max(initial=0))
        spec.check(max_ts, len(self.interner), max_tx)
        hi = np.where(self.valid, self.ts.astype(np.int32), I32_MAX)
        lo = np.where(self.valid, spec.pack_lo(self.site, self.tx), I32_MAX)
        return hi, lo

    def cause_lanes(self, spec: Optional[PackSpec] = None):
        """(hi, lo) lanes of each node's cause id — any id-shaped cause,
        even one living in another replica's tree (merges resolve causes
        against the union) — or (-1, -1) when the cause is not an id
        (root sentinel, key causes, padding). Precomputed in
        ``from_nodes_map`` with its ``spec``; asking for a different
        layout (or one the ids overflow) is an error, not a silent
        mismatch against ``id_lanes``."""
        if spec is not None and spec != self.spec:
            raise ValueError(
                "cause_lanes were packed with the from_nodes_map spec "
                f"{self.spec}; re-marshal to use {spec}"
            )
        if not self.spec_ok:
            raise OverflowError(
                "ids exceed the PackSpec bit layout; device lanes are "
                "unavailable (host backends can still use cause_idx)"
            )
        return self.cause_hi, self.cause_lo


def map_lanes(nodes_map):
    """``(sorted_nodes, cause_idx, key_rank, vclass, keys)`` for a map
    tree — the shared marshaller of the native and device map weavers.

    Key resolution follows the pure weaver exactly (single level: an
    id-caused node's key is its target's cause, map.cljc:31-37), so the
    accelerated domain requires id-caused nodes to target key-caused
    nodes — everything the collection/base APIs generate. Anything else
    raises ``OutsideDomain`` and the caller falls back to pure.
    """
    from ..ids import is_id

    ids = sorted(nodes_map)
    idx_of = {nid: i for i, nid in enumerate(ids)}
    n = len(ids)
    cause_idx = np.full(n, -1, np.int32)
    key_rank = np.full(n, -1, np.int32)
    vclass = np.zeros(n, np.int32)
    keys = []
    key_ordinal = {}
    nodes = []
    for i, nid in enumerate(ids):
        cause, value = nodes_map[nid]
        vclass[i] = vclass_of(value)
        if is_id(cause):
            ci = idx_of.get(tuple(cause), -1)
            if ci < 0:
                raise OutsideDomain()  # dangling target
            target_cause = nodes_map[tuple(cause)][0]
            if is_id(target_cause):
                raise OutsideDomain()  # id-caused targeting id-caused
            cause_idx[i] = ci
        else:
            k = cause
            if k not in key_ordinal:
                key_ordinal[k] = len(keys)
                keys.append(k)
            key_rank[i] = key_ordinal[k]
        nodes.append((nid, cause, value))
    return nodes, cause_idx, key_rank, vclass, keys


def rebuild_map_weave(nodes, key_of, order, keys):
    """Split an accelerated forest ordering back into the per-key weave
    dict — shared by the native and device map weavers. ``nodes`` are
    host triples in lane order, ``key_of[i]`` each lane's resolved key
    ordinal, ``order`` the lanes in global weave order. Key-caused
    nodes' in-weave cause is rewritten to the root sentinel
    (map.cljc:77)."""
    from ..ids import ROOT_ID, ROOT_NODE, is_id

    weave = {}
    for i in order:
        nid, cause, value = nodes[i]
        k = keys[key_of[i]]
        in_weave_cause = cause if is_id(cause) else ROOT_ID
        weave.setdefault(k, [ROOT_NODE]).append((nid, in_weave_cause, value))
    return weave
