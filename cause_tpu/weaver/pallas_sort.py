"""In-VMEM bitonic sort — the Pallas answer to the kernel sort cost.

The weave kernels sort [B, U] token tables four times per wave
(U ~2.3k, B ~1k). XLA's TPU ``lax.sort`` lowers a comparator loop; the
XLA-level bitonic network (``bitonic.bitonic_sort``) replaces it with
~78 elementwise compare-exchange stages, but each stage round-trips
every operand through HBM — ~10 GB per full-size sort, hopeless on
bandwidth. This module runs the SAME network inside one Pallas kernel
per 8-row block: operands load into VMEM once, all stages run
VMEM-resident on the VPU, results store once. HBM traffic collapses to
one read + one write per operand.

Mosaic shapes the design (cf. pallas_ops, tests/test_pallas_lowering):

- compare-exchange partners are fetched with ``jnp.roll`` along the
  lane axis (XOR-partner pairs at distance j never wrap, so a roll in
  each direction + a direction mask IS the partner permutation) — no
  gathers, no (nb, 2, j) reshapes whose last dim breaks the 128-lane
  tiling rule;
- the network is statically unrolled at trace time (log2(P)^2 / 2
  stages — 78 at P=4096), every stage pure elementwise select;
- batching maps onto an explicit (8, P) grid via
  ``jax.custom_batching.custom_vmap`` (a squeezed leading block dim
  fails the tiling rule), mirroring ``euler_walk``.

Contract: identical to ``bitonic.bitonic_sort`` — int32 operands,
ascending lexicographic over the first ``num_keys`` operands, an
implicit original-position key appended so the result is the unique
deterministic stable order (== stable ``lax.sort``), padding with
int32 max beyond the true length. ``CAUSE_TPU_SORT=pallas`` flips the
kernels here at trace time (see ``bitonic.sort_pairs``).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["pallas_bitonic_sort"]

_I32_MAX = jnp.iinfo(jnp.int32).max
_ROWS = 8  # rows per grid block (the Mosaic sublane tiling unit)


def _interpret() -> bool:
    """Interpret off-TPU (tests, dryrun); compile via Mosaic on TPU."""
    return jax.default_backend() != "tpu"


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _kernel_body(refs, n_ops: int, num_keys: int):
    """One block: load every operand, run the whole network in VMEM,
    store. ``refs`` = n_ops input refs + n_ops output refs. The
    original-position tie-break key is generated IN-KERNEL from
    ``broadcasted_iota`` (it is exactly arange(P) per row) rather than
    passed as an operand — one less int32 array round-tripping HBM.
    Key order: keys 0..num_keys-1, then the position key, exactly as
    bitonic.bitonic_sort."""
    ins = refs[:n_ops]
    outs = refs[n_ops:]
    R, P = ins[0].shape
    iota = lax.broadcasted_iota(jnp.int32, (R, P), 1)
    arrs = [r[:] for r in ins] + [iota]
    key_pos = list(range(num_keys)) + [n_ops]

    k = 2
    while k <= P:
        j = k // 2
        while j >= 1:
            lower = (iota & j) == 0
            # final merge (k == P): every i has bit P clear, so asc is
            # all-true automatically — no special case needed
            asc = (iota & k) == 0
            partners = [
                jnp.where(lower,
                          jnp.roll(x, -j, axis=1),
                          jnp.roll(x, j, axis=1))
                for x in arrs
            ]
            # strict total order (the iota key breaks every tie), so
            # one lexicographic compare decides the exchange
            lt = None
            eq = None
            for kp in key_pos:
                a, b = arrs[kp], partners[kp]
                this_lt = a < b
                this_eq = a == b
                if lt is None:
                    lt, eq = this_lt, this_eq
                else:
                    lt = lt | (eq & this_lt)
                    eq = eq & this_eq
            want_self = lt == (lower == asc)
            arrs = [jnp.where(want_self, x, p)
                    for x, p in zip(arrs, partners)]
            j //= 2
        k *= 2

    for o, x in zip(outs, arrs):  # arrs[n_ops] (the position key) is
        o[:] = x                  # dropped: len(outs) == n_ops


@lru_cache(maxsize=None)
def _build(n_ops: int, num_keys: int):
    """The (batched, single) pallas callables for an operand count.
    Cached so repeated traces reuse the same custom_vmap object."""

    def kernel(*refs):
        _kernel_body(refs, n_ops, num_keys)

    def batch_call(*ops):
        B, P = ops[0].shape
        Bp = -(-B // _ROWS) * _ROWS
        if Bp != B:
            # padded rows sort their (MAX-key, iota) lanes — discarded
            ops = tuple(
                jnp.pad(x, ((0, Bp - B), (0, 0)),
                        constant_values=_I32_MAX if i < num_keys else 0)
                for i, x in enumerate(ops))
        if pltpu is not None:
            spec = pl.BlockSpec((_ROWS, P), lambda b: (b, 0),
                                memory_space=pltpu.VMEM)
        else:  # pragma: no cover - CPU-only jaxlib
            spec = pl.BlockSpec((_ROWS, P), lambda b: (b, 0))
        out = pl.pallas_call(
            kernel,
            grid=(Bp // _ROWS,),
            in_specs=[spec] * n_ops,
            out_specs=[spec] * n_ops,
            out_shape=[jax.ShapeDtypeStruct((Bp, P), jnp.int32)] * n_ops,
            interpret=_interpret(),
        )(*ops)
        return tuple(x[:B] for x in out)

    @jax.custom_batching.custom_vmap
    def single(*ops):
        P = ops[0].shape[0]
        if pltpu is not None:
            spec = pl.BlockSpec(memory_space=pltpu.VMEM)
        else:  # pragma: no cover - CPU-only jaxlib
            spec = pl.BlockSpec()
        out = pl.pallas_call(
            kernel,
            in_specs=[spec] * n_ops,
            out_specs=[spec] * n_ops,
            out_shape=[jax.ShapeDtypeStruct((1, P), jnp.int32)] * n_ops,
            interpret=_interpret(),
        )(*(x.reshape(1, P) for x in ops))
        return tuple(x.reshape(P) for x in out)

    @single.def_vmap
    def _single_vmap(axis_size, in_batched, *ops):
        ops = tuple(
            x if b else jnp.broadcast_to(x, (axis_size,) + x.shape)
            for x, b in zip(ops, in_batched))
        return batch_call(*ops), (True,) * n_ops

    return single, batch_call


def pallas_bitonic_sort(operands, num_keys: int = 1):
    """Sort int32 arrays along the last axis inside one VMEM-resident
    Pallas kernel (see module docstring; contract identical to
    ``bitonic.bitonic_sort``)."""
    operands = tuple(operands)
    for x in operands:
        if x.dtype != jnp.int32:
            raise TypeError(f"pallas sort is int32-only, got {x.dtype}")
    n = operands[0].shape[-1]
    P = max(128, _next_pow2(n))
    lead = operands[0].shape[:-1]
    arrs = []
    for i, x in enumerate(operands):
        if P != n:
            fill = _I32_MAX if i < num_keys else 0
            pad = jnp.full(lead + (P - n,), fill, x.dtype)
            x = jnp.concatenate([x, pad], axis=-1)
        arrs.append(x)
    # the deterministic-stability position key is generated inside the
    # kernel (broadcasted_iota), not passed as an operand

    single, batch_call = _build(len(arrs), num_keys)
    if not lead:
        # 1-D call (the kernels' per-row form; under vmap the
        # custom_vmap rule swaps in the gridded batch kernel)
        out = single(*arrs)
    else:
        # direct multi-dim call: flatten the lead dims onto the grid
        B = 1
        for d in lead:
            B *= d
        out = batch_call(*(x.reshape(B, P) for x in arrs))
        out = tuple(x.reshape(lead + (P,)) for x in out)

    if P != n:
        out = tuple(x[..., :n] for x in out)
    return tuple(out)
