"""The v5 segment-union merge kernel: merge cost scales with divergence.

Every kernel so far (v1-v4) pays full node width for the union sort
and the flag/scan passes even though replicas of a shared document are
IDENTICAL over almost all of it. v5 moves the union to *segment*
granularity (per-tree chain runs, marshal-extracted by
``segments.tree_segments``) and only explodes a segment to node
tokens where replicas actually interact:

E1. its id interval overlaps another segment's (divergent edits
    interleave), unless the two are exact dense twins (the shared
    root/base prefix every replica carries — those dedupe wholesale,
    exactly: a dense segment's member ids are fully determined by
    (min, max, len));
E2. some other segment head's *cause* stabs its interior — including
    its tail when the tail is special with members before it, because
    the host jump of an external child would walk through the tail
    into the interior and split the run there (the v4 contested rule).

Everything that survives rides the union as ONE sort token carrying
its length; exploded segments contribute one token per lane. The
union pipeline is then exactly jaxw4's — adjacency, host-case, glue,
contested, chain runs, sibling sort, Euler ranking — run at token
width (~divergence size) with token lengths as weights, and the final
per-lane ranks/visibility materialize over the full lane width with
only elementwise passes, cumulative scans, and small scatters: no
full-width sort, gather, or binary search anywhere.

For the north-star shape (1024 pairs x 10k nodes, ~2k-node divergence)
that removes ~95% of the full-width work v4 still did. For a single
tree (the API reweave path) nothing explodes and the device work is
just the segment ordering. Semantics remain EXACT vs the pure oracle
and v1 (tests/test_jax_v5.py); like v2-v4 the kernel takes static
budgets (``s`` is the table size, ``u_max`` tokens, ``k_max`` runs)
and raises an overflow flag instead of corrupting.

Twin-dedupe integrity: the twin test compares endpoints, length,
density, head vclass + cause, tail-specialness, AND a position
-weighted vclass checksum (``sg_vsum``), so a same-id twin whose
interior value CLASSES or structure diverge (append-only violation
from a corrupt replica) explodes and trips the node-level ``conflict``
check instead of vanishing wholesale. What the device still cannot
see is host VALUE bytes — two twins identical in ids/classes/causes
but differing in, say, the string payload of one node pass the device
unflagged; the API paths validate bodies host-side
(shared.union_nodes, WaveResult.merged) for exactly that reason.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..switches import resolve
from .arrays import I32_MAX, VCLASS_H_HIDE, VCLASS_HIDE
from .jaxw import _euler_rank, _link_children
from .jaxw3 import _shift1
from .bitonic import sort_pairs
from .gatherops import (searchsorted_iota_right,
                        searchsorted_targets_left, take1d)

__all__ = [
    "merge_weave_kernel_v5",
    "batched_merge_weave_v5",
]


def _stage_ck(*xs):
    """Scalar checksum keeping every given array live (profiling)."""
    total = jnp.float32(0)
    for x in xs:
        total = total + jnp.sum(x.astype(jnp.float32))
    return total


def _hint_kw(sorted_: bool = False, unique: bool = False) -> dict:
    """Scatter-annotation kwargs under ``CAUSE_TPU_SCATTER=hint``
    (trace-time A/B switch): an XLA TPU scatter serializes to handle
    potential duplicate indices; the kernel's scatter sites below are
    rewritten so their index streams are unique (and mostly sorted) BY
    CONSTRUCTION — invalid entries dump into per-position spread slots
    past the live range instead of sharing one dump index — so the
    annotations are provable, not merely test-passing. Off by default
    so the hardware A/B isolates their effect."""
    if resolve("CAUSE_TPU_SCATTER") != "hint":
        return {}
    kw = {}
    if sorted_:
        kw["indices_are_sorted"] = True
    if unique:
        kw["unique_indices"] = True
    return kw


def _lt(a1, a2, b1, b2):
    return (a1 < b1) | ((a1 == b1) & (a2 < b2))


def _le(a1, a2, b1, b2):
    return (a1 < b1) | ((a1 == b1) & (a2 <= b2))


def _eq(a1, a2, b1, b2):
    return (a1 == b1) & (a2 == b2)


def _pair_cummax(hi, lo):
    """Inclusive running lexicographic max over (hi, lo) pairs."""

    def op(a, b):
        ah, al = a
        bh, bl = b
        take_b = _lt(ah, al, bh, bl)
        return jnp.where(take_b, bh, ah), jnp.where(take_b, bl, al)

    return lax.associative_scan(op, (hi, lo))


def _pair_search_le(kh, kl, qh, ql, size):
    """For each query id, the rightmost index i in the sorted (kh, kl)
    arrays with key[i] <= query (-1 if none).

    Default: a fori binary search (log2(size) rounds of table
    gathers). ``CAUSE_TPU_SEARCH=matrix`` or ``matrix-table``
    (trace-time) counts key<=query over the full [q, size] comparison
    matrix instead — O(size^2) elementwise work that streams on the
    VPU with zero random access; at the segment-table widths
    (size ~512) that is cheaper on TPU than 10 gather rounds.
    (``matrix-table`` applies matrix search HERE only, leaving the
    U-width searchsorted histogram in gatherops untouched — see its
    docstring for why.)"""
    from ..obs import span as _span

    mode = resolve("CAUSE_TPU_SEARCH")
    if mode in ("matrix", "matrix-table"):
        with _span("weave.search", strategy=mode, site="table",
                   size=int(size)):
            le = _le(kh[None, :], kl[None, :], qh[:, None], ql[:, None])
            return jnp.sum(le, axis=1).astype(jnp.int32) - 1

    with _span("weave.search", strategy="binary", site="table",
               size=int(size)):
        steps = 1
        while (1 << steps) < size + 1:
            steps += 1

        def body(_, c):
            lo_b, hi_b = c
            mid = (lo_b + hi_b + 1) // 2  # invariant: key[lo_b] <= q
            ms = jnp.clip(mid, 0, size - 1)
            ok = _le(take1d(kh, ms), take1d(kl, ms), qh, ql)
            return (jnp.where(ok, mid, lo_b),
                    jnp.where(ok, hi_b, mid - 1))

        lo_b, _ = lax.fori_loop(
            0, steps, body,
            (jnp.full_like(qh, -1), jnp.full_like(qh, size - 1)),
        )
        return lo_b


def _merge_weave_kernel_v5_impl(hi, lo, cci, vclass, valid, seg,
                                sg_min_hi, sg_min_lo, sg_max_hi,
                                sg_max_lo, sg_len, sg_lane0, sg_dense,
                                sg_tail_special, sg_valid, sg_vsum,
                                u_max: int, k_max: int,
                                stage: str | None = None,
                                euler: str = "doubling"):
    """Union + reweave at segment granularity for one replica set.

    Node lanes as in v4 (``hi/lo/cci/vclass/valid`` — trees
    concatenated, each id-sorted) plus ``seg`` (each lane's segment
    ordinal) and the ``SEG_LANE_KEYS`` tables in ascending-lane marshal
    order. Returns ``(rank, visible, conflict, overflow)`` with rank
    and visibility indexed by CONCAT lane (not by sorted position —
    there is no full-width sorted order here).

    ``stage`` (static; profiling only) returns early with one scalar
    checksum of that phase's live outputs, so a prefix of the pipeline
    can be timed on hardware without dead-code elimination hiding it:
    ``"A"`` segment ordering + explode/dedupe, ``"B"`` token
    construction, ``"C"`` token sort + dedupe, ``"D"`` cause
    resolution, ``"E"`` token-width ranking + kills. ``None`` (the
    default, the only non-test caller mode) runs the full kernel.
    """
    N = hi.shape[0]
    S = sg_len.shape[0]
    sidx = jnp.arange(S, dtype=jnp.int32)
    BIG = I32_MAX

    # ================= A. segment ordering + explode/dedupe =========
    kh = jnp.where(sg_valid, sg_min_hi, BIG)
    kl = jnp.where(sg_valid, sg_min_lo, BIG)
    s_mh, s_ml, s_src = sort_pairs((kh, kl, sidx), num_keys=2)
    s_Mh = take1d(sg_max_hi, s_src)
    s_Ml = take1d(sg_max_lo, s_src)
    s_va = take1d(sg_valid, s_src)
    s_len = jnp.where(s_va, take1d(sg_len, s_src), 0)
    s_lane0 = take1d(sg_lane0, s_src)
    s_dense = take1d(sg_dense, s_src)
    s_tsp = take1d(sg_tail_special, s_src)
    s_vsum = take1d(sg_vsum, s_src)

    # head body fields (shared by the twin test and the E2 stabs)
    s_hvc = take1d(vclass, jnp.clip(s_lane0, 0, N - 1))
    c_lane = take1d(cci, jnp.clip(s_lane0, 0, N - 1))
    has_c = s_va & (c_lane >= 0)
    c_hi = jnp.where(has_c, take1d(hi, jnp.clip(c_lane, 0, N - 1)), -1)
    c_lo = jnp.where(has_c, take1d(lo, jnp.clip(c_lane, 0, N - 1)), -1)

    # twin groups: adjacent exact-equal dense segments dedupe wholesale.
    # Equality covers the endpoints, length, density, the head's value
    # class and cause id, the tail-special flag, and the position
    # -weighted vclass checksum (sg_vsum) — so a same-id segment whose
    # INTERIOR body classes differ (a corrupt replica violating
    # append-only) fails the test, explodes, and the node-level
    # duplicate check reports the conflict. Host VALUES remain a
    # host-side check (shared.union_nodes / WaveResult.merged): the
    # device never sees them.
    p_mh, p_ml = _shift1(s_mh, -1), _shift1(s_ml, -1)
    same_prev = (
        _eq(s_mh, s_ml, p_mh, p_ml)
        & _eq(s_Mh, s_Ml, _shift1(s_Mh, -1), _shift1(s_Ml, -1))
        & (s_len == _shift1(s_len, -1))
        & s_dense & _shift1(s_dense, False)
        & (s_hvc == _shift1(s_hvc, -1))
        & (s_tsp == _shift1(s_tsp, False))
        & (s_vsum == _shift1(s_vsum, -1))
        & _eq(c_hi, c_lo, _shift1(c_hi, -1), _shift1(c_lo, -1))
        & s_va & _shift1(s_va, False)
        & (sidx > 0)
    )
    grp_start = ~same_prev
    grp = jnp.cumsum(grp_start.astype(jnp.int32)) - 1

    # per-group interval tables (twins share min/max by construction).
    # Scatter indices: group ordinals at group starts (strictly
    # increasing), everything else dumped into its own spread slot past
    # S — unique by construction, so the scatter needs no duplicate
    # handling (annotated under CAUSE_TPU_SCATTER=hint).
    is_start = grp_start & s_va
    gsl = jnp.where(is_start, grp, S + sidx)
    uniq = _hint_kw(unique=True)

    def _gtable(vals, fill):
        return jnp.full(2 * S, fill, jnp.int32).at[gsl].set(
            jnp.where(is_start, vals, fill), **uniq)[:S]

    g_mh = _gtable(s_mh, BIG)
    g_ml = _gtable(s_ml, BIG)
    g_Mh = _gtable(s_Mh, -1)
    g_Ml = _gtable(s_Ml, -1)

    # E1: overlap with any earlier group (prefix pair-max of maxes,
    # exclusive) or the next group (its min is the smallest later min)
    pmh, pml = _pair_cummax(g_Mh, g_Ml)
    pmh_e, pml_e = _shift1(pmh, -1), _shift1(pml, -1)
    gi = jnp.clip(grp, 0, S - 1)
    ov_before = _le(s_mh, s_ml, take1d(pmh_e, gi), take1d(pml_e, gi))
    nxt_mh = jnp.concatenate([g_mh[1:], jnp.full((1,), BIG, jnp.int32)])
    nxt_ml = jnp.concatenate([g_ml[1:], jnp.full((1,), BIG, jnp.int32)])
    ov_after = _le(take1d(nxt_mh, gi), take1d(nxt_ml, gi), s_Mh, s_Ml)
    explode = s_va & (ov_before | ov_after)

    # E2: head-cause stabs. Candidate = rightmost group with min <= c.
    pg = _pair_search_le(g_mh, g_ml, c_hi, c_lo, S)
    pgc = jnp.clip(pg, 0, S - 1)
    # group tables for the stabbed group: len/tail-specialness of its
    # representative member (first of group; twins agree)
    rep = jnp.full(2 * S, 0, jnp.int32).at[gsl].set(
        jnp.where(is_start, sidx, 0), **uniq)[:S]
    rep_pg = take1d(rep, pgc)
    r_len = take1d(s_len, rep_pg)
    r_tsp = take1d(s_tsp, rep_pg)
    gm_h, gm_l = take1d(g_mh, pgc), take1d(g_ml, pgc)
    gM_h, gM_l = take1d(g_Mh, pgc), take1d(g_Ml, pgc)
    stab = has_c & (pg >= 0) & _le(gm_h, gm_l, c_hi, c_lo) & (
        _lt(c_hi, c_lo, gM_h, gM_l)
        | (_eq(c_hi, c_lo, gM_h, gM_l) & r_tsp & (r_len > 1))
    )
    g_stabbed = jnp.zeros(S, bool).at[
        jnp.where(stab, pgc, S - 1)
    ].set(True, mode="drop")
    # make the last slot honest (it may have been used as a dump)
    g_stabbed = g_stabbed.at[S - 1].set(
        jnp.any(stab & (pgc == S - 1)))
    explode = explode | (s_va & take1d(g_stabbed, gi))

    twin_drop = same_prev & ~explode
    survive = s_va & ~explode & ~twin_drop
    if stage == "A":
        return _stage_ck(explode, survive, grp)

    # ================= B. token construction ========================
    tok_cnt = jnp.where(survive, 1, jnp.where(s_va & explode, s_len, 0))
    tc_cum = jnp.cumsum(tok_cnt)
    tb = tc_cum - tok_cnt  # exclusive: first token slot per sorted seg
    n_tok = tc_cum[-1]
    U = u_max
    uidx = jnp.arange(U, dtype=jnp.int32)
    u_ok = uidx < jnp.minimum(n_tok, U)
    overflow_u = n_tok > U

    owner = searchsorted_iota_right(tc_cum, U)
    oc = jnp.clip(owner, 0, S - 1)
    off = uidx - take1d(tb, oc)
    o_expl = take1d(s_va, oc) & (~take1d(survive, oc))
    t_lane = jnp.clip(
        take1d(s_lane0, oc) + jnp.where(o_expl, off, 0), 0, N - 1
    )
    t_hi = jnp.where(u_ok, take1d(hi, t_lane), BIG)
    t_lo = jnp.where(u_ok, take1d(lo, t_lane), BIG)
    t_len = jnp.where(u_ok, jnp.where(o_expl, 1, take1d(s_len, oc)), 0)
    t_vc = jnp.where(u_ok, take1d(vclass, t_lane), 0)
    t_tail_lane = t_lane + t_len - 1
    t_tsp = jnp.where(
        o_expl, t_vc > 0, take1d(s_tsp, oc)
    ) & u_ok
    if stage == "B":
        return _stage_ck(t_hi, t_lo, t_len, t_tsp)

    # token_of_lane machinery (PRESORT token ids). A cause lane inside
    # a twin-DROPPED segment copy (tree B's own copy of the shared
    # base) must resolve to the KEPT twin's token: group-start fill
    # gsp redirects any twin member to its group's first (kept) member.
    inv_s = jnp.zeros(S, jnp.int32).at[s_src].set(sidx, **uniq)
    seg_expl_sorted = s_va & explode
    gsp = lax.cummax(jnp.where(grp_start, sidx, -1))

    def token_of_lane(p):
        pc = jnp.clip(p, 0, N - 1)
        m = jnp.clip(take1d(seg, pc), 0, S - 1)
        ss2 = take1d(inv_s, m)
        ex = take1d(seg_expl_sorted, ss2)
        owner_ss = jnp.where(ex, ss2, take1d(gsp, ss2))
        return (take1d(tb, owner_ss)
                + jnp.where(ex, pc - take1d(sg_lane0, m), 0)).astype(jnp.int32)

    if stage == "_AB":
        # internal handoff for the fused v5f pipeline (jaxw5f): every
        # phase-A/B product the token kernels consume, plus the
        # coverage inputs the F glue needs. Not a profiling stage —
        # returns a namespace of traced arrays, so only jaxw5f calls
        # it (inside its own jit), never the jitted entry points.
        from types import SimpleNamespace

        return SimpleNamespace(
            t_hi=t_hi, t_lo=t_lo, t_len=t_len, t_vc=t_vc,
            t_tsp=t_tsp, t_lane=t_lane, token_of_lane=token_of_lane,
            overflow_u=overflow_u, survive=survive, inv_s=inv_s,
            uidx=uidx)

    # ================= C. sort tokens, dedupe =======================
    # With a network sort (bitonic/pallas) the payload fields RIDE the
    # sort — one roll+select per stage each, all streaming — instead of
    # five post-sort permutation gathers (the expensive primitive the
    # strategy exists to avoid). The matrix rank-sort rides too: its
    # payload apply is a streaming rowgather per operand, so carrying
    # payloads keeps a sort=matrix-only A/B free of per-element
    # gathers. With the default comparator sort, extra variadic
    # operands slow the comparator, so the gather form stays.
    # Identical results either way: same keys, same implicit-iota
    # stability, and payload-carry == gather-by-permutation.
    su_src_in = uidx
    ride = resolve("CAUSE_TPU_SORT") in ("bitonic", "pallas", "matrix")
    if ride:
        (st_hi, st_lo, t_src, sv_len, sv_vc, sv_tsp_i,
         sv_lane) = sort_pairs(
            (t_hi, t_lo, su_src_in, t_len, t_vc,
             t_tsp.astype(jnp.int32), t_lane), num_keys=2)
        sv_tsp = sv_tsp_i.astype(bool)
        sv_tail_lane = sv_lane + sv_len - 1  # == permuted t_tail_lane
    else:
        st_hi, st_lo, t_src = sort_pairs((t_hi, t_lo, su_src_in),
                                         num_keys=2)
        g = lambda arr: take1d(arr, t_src)  # presort -> sorted order
        sv_len, sv_vc, sv_tsp = g(t_len), g(t_vc), g(t_tsp)
        sv_lane, sv_tail_lane = g(t_lane), g(t_tail_lane)
    inv_t = jnp.zeros(U, jnp.int32).at[t_src].set(uidx, **uniq)

    tva = ~((st_hi == BIG) & (st_lo == BIG))
    sdup = (
        _eq(st_hi, st_lo, _shift1(st_hi, -1), _shift1(st_lo, -1))
        & (uidx > 0) & tva
    )
    keep_t = tva & ~sdup
    if stage == "C":
        return _stage_ck(st_hi, keep_t, sv_lane, inv_t)

    # ================= D. token cause resolution ====================
    cl = jnp.where(tva, take1d(cci, jnp.clip(sv_lane, 0, N - 1)), -1)
    cause_u = token_of_lane(cl)
    cause_su_raw = take1d(inv_t, jnp.clip(cause_u, 0, U - 1))
    # redirect to the kept head of a duplicate token group: dups are
    # adjacent after the sort, so a kept-head fill redirects them
    thead = lax.cummax(jnp.where(keep_t, uidx, -1))
    cause_su = jnp.where(
        cl >= 0, take1d(thead, jnp.clip(cause_su_raw, 0, U - 1)), 0
    ).astype(jnp.int32)

    special_t = keep_t & (sv_vc > 0)
    is_root_t = keep_t & (uidx == 0)
    rel_t = keep_t & ~is_root_t

    # host walk (lane-level, at token width): first non-special lane
    # on the cause chain
    def wcond(c):
        p, i = c
        pc = jnp.clip(p, 0, N - 1)
        on = rel_t & ~special_t & (p >= 0) & (take1d(vclass, pc) > 0)
        return (i < N) & jnp.any(on)

    def wbody(c):
        p, i = c
        pc = jnp.clip(p, 0, N - 1)
        on = rel_t & ~special_t & (p >= 0) & (take1d(vclass, pc) > 0)
        return jnp.where(on, take1d(cci, pc), p), i + 1

    host_lane, _ = lax.while_loop(wcond, wbody, (cl, jnp.int32(0)))
    host_su = jnp.where(
        host_lane >= 0,
        take1d(thead,
               jnp.clip(take1d(inv_t,
                               jnp.clip(token_of_lane(host_lane),
                                        0, U - 1)), 0, U - 1)),
        0,
    ).astype(jnp.int32)
    parent_su = jnp.where(special_t, cause_su, host_su)

    conflict = jnp.any(
        sdup & (
            (sv_vc != _shift1(sv_vc, 0))
            | (cause_su != _shift1(cause_su, 0))
            | (sv_len != _shift1(sv_len, 0))
        )
    )
    if stage == "D":
        return _stage_ck(parent_su, cause_su, conflict)

    # ================= E. v4 pipeline at token width ================
    wcum = jnp.cumsum(jnp.where(keep_t, sv_len, 0))
    wstart = wcum - jnp.where(keep_t, sv_len, 0)
    n_kept_nodes = wcum[-1]

    sp_pack = lax.cummax(
        jnp.where(keep_t, uidx * 2 + sv_tsp.astype(jnp.int32), -1)
    )
    sp_prev = _shift1(sp_pack, -1)
    prev_kept = jnp.where(sp_prev >= 0, sp_prev >> 1, -1)
    prev_kept_tsp = (sp_prev >= 0) & (sp_prev % 2 == 1)

    adj = rel_t & (cause_su == prev_kept) & (prev_kept >= 0)
    host_case = adj & ~special_t & prev_kept_tsp
    irregular = rel_t & (~adj | host_case)

    extra = jnp.zeros(U, jnp.int32).at[
        jnp.where(irregular, parent_su, U - 1)
    ].add(1, mode="drop")
    extra = extra.at[U - 1].set(
        jnp.sum(jnp.where(irregular & (parent_su == U - 1), 1, 0)))
    ec_pack = lax.cummax(
        jnp.where(keep_t, uidx * 2 + (extra > 0).astype(jnp.int32), -1)
    )
    ec_prev = _shift1(ec_pack, -1)
    prev_contested = (ec_prev >= 0) & (ec_prev % 2 == 1)
    glued = adj & ~host_case & ~prev_contested

    run_start = keep_t & ~glued
    rs_cum = jnp.cumsum(run_start.astype(jnp.int32))
    run_id = rs_cum - 1
    n_runs = rs_cum[-1]
    overflow_k = n_runs > k_max

    targets = jnp.arange(1, k_max + 1, dtype=jnp.int32)
    head_tok = searchsorted_targets_left(rs_cum, k_max)
    r_valid = targets <= jnp.minimum(n_runs, k_max)
    hc = jnp.clip(head_tok, 0, U - 1)

    h_parent = jnp.where(
        take1d(irregular, hc), take1d(parent_su, hc),
        jnp.where(take1d(adj, hc), take1d(prev_kept, hc), -1),
    )
    h_parent = jnp.where(r_valid & ~take1d(is_root_t, hc), h_parent, -1)
    parent_run = jnp.where(
        h_parent >= 0, take1d(run_id, jnp.clip(h_parent, 0, U - 1)), -1
    ).astype(jnp.int32)

    h_special = take1d(special_t, hc)
    h_w = take1d(wstart, hc)
    nxt_w = jnp.concatenate([h_w[1:], h_w[:1]])
    run_w = jnp.where(
        r_valid,
        jnp.where(targets == n_runs, n_kept_nodes - h_w, nxt_w - h_w),
        0,
    ).astype(jnp.int32)

    parent_sort = jnp.where(r_valid & (parent_run >= 0), parent_run, k_max)
    packed = parent_sort * 2 + (~h_special).astype(jnp.int32)
    kidx_r = jnp.arange(k_max, dtype=jnp.int32)
    sord = sort_pairs((packed, -hc, kidx_r), num_keys=2)[2]
    fc, ns = _link_children(sord, parent_sort)
    parent_up = jnp.where(r_valid & (parent_run >= 0), parent_run, -1)
    if euler == "walk":
        from .pallas_ops import euler_walk

        base_run = euler_walk(fc, ns, parent_up, run_w, k_max)
    else:
        base_run, _ = _euler_rank(fc, ns, parent_up, run_w)

    # expand run bases to token bases (node units): delta-scatter at
    # run-head tokens + one cumsum over U, then add within-run offset
    delta = jnp.where(
        r_valid,
        base_run - jnp.concatenate([jnp.zeros((1,), base_run.dtype),
                                    base_run[:-1]]),
        0,
    )
    # valid targets are a prefix with strictly increasing head tokens;
    # invalid ones dump into spread slots past U — the index stream is
    # globally sorted AND unique by construction (no shared dump slot,
    # no collision fix-up needed)
    scat_du = jnp.where(r_valid, hc, U + kidx_r)
    delta_u = jnp.zeros(U + k_max, jnp.int32).at[scat_du].set(
        delta.astype(jnp.int32),
        **_hint_kw(sorted_=True, unique=True))[:U]
    base_ff = jnp.cumsum(delta_u)
    ffw = lax.cummax(jnp.where(run_start, wstart, -1))
    rank_tok = jnp.where(
        keep_t, base_ff + (wstart - ffw), N
    ).astype(jnp.int32)

    # -------- token-level kills (victims as lanes) ------------------
    hideish = (sv_vc == VCLASS_HIDE) | (sv_vc == VCLASS_H_HIDE)
    kg = glued & hideish
    vict_inrun = jnp.where(
        kg, take1d(sv_tail_lane, jnp.clip(prev_kept, 0, U - 1)), N
    )

    # preorder-successor run: the run with the next-larger base. base
    # values are node-unit positions (up to N), so find successors by
    # sorting runs on base instead of scattering over node positions.
    bkey = jnp.where(r_valid, base_run, BIG)
    b_sorted, b_src = sort_pairs((bkey, kidx_r), num_keys=1)
    succ_in_sorted = jnp.concatenate([
        b_src[1:], jnp.full((1,), -1, jnp.int32)
    ])
    succ_valid = jnp.concatenate([
        b_sorted[1:] != BIG, jnp.zeros((1,), bool)
    ])
    succ_of = jnp.full(k_max, -1, jnp.int32).at[b_src].set(
        jnp.where(succ_valid, succ_in_sorted, -1), **uniq
    )
    succ_run = jnp.where(r_valid, succ_of, -1)
    s_c = jnp.clip(
        jnp.where(succ_run >= 0,
                  take1d(hc, jnp.clip(succ_run, 0, k_max - 1)), 0),
        0, U - 1,
    )
    s_is_hide = (succ_run >= 0) & take1d(hideish, s_c)
    nxt_head = jnp.concatenate([hc[1:], hc[:1]])
    tail_tok = jnp.where(
        targets == n_runs,
        jnp.maximum(sp_pack[-1] >> 1, 0),
        take1d(prev_kept, jnp.clip(nxt_head, 0, U - 1)),
    ).astype(jnp.int32)
    t_cc = jnp.clip(tail_tok, 0, U - 1)
    # succ head's cause must BE the run's tail node — compared at
    # token level (cause_su is duplicate-redirected; a hide arriving
    # from another replica names its own dropped copy of the tail)
    kill_tail = r_valid & s_is_hide & (take1d(cause_su, s_c) == tail_tok)
    vict_tail = jnp.where(kill_tail, take1d(sv_tail_lane, t_cc), N)
    if stage == "E":
        # conflict included so prefix increments stay strictly
        # cumulative over stage D's reduction
        return _stage_ck(rank_tok, vict_inrun, vict_tail, kill_tail,
                         conflict)

    # ================= F. expansion to concat lanes =================
    # token base + token lane, in LANE order (sort tokens by lane) so
    # deltas scatter + cumsum reconstructs per-lane values without any
    # full-width gather
    lane_key = jnp.where(keep_t & (rank_tok < N), sv_lane, N)
    if ride:  # rank rides the lane sort (see phase C note)
        lk, tok_at, tb_l = sort_pairs((lane_key, uidx, rank_tok),
                                      num_keys=1)
    else:
        lk, tok_at = sort_pairs((lane_key, uidx), num_keys=1)
        tb_l = take1d(rank_tok, tok_at)

    # pieces shared by both F backends: per-lane coverage flags, the
    # token-level kill scatters (victims can duplicate — genuine
    # scatters, U-width, stay in XLA either way), and the root lane
    seg_cov = sg_valid & take1d(survive, inv_s)
    killed_sc = jnp.zeros(N + 1, bool)
    killed_sc = killed_sc.at[jnp.where(kg, vict_inrun, N)].set(
        True, mode="drop")
    killed_sc = killed_sc.at[jnp.where(kill_tail, vict_tail, N)].set(
        True, mode="drop")
    root_lane = jnp.zeros(N, bool).at[
        jnp.clip(sv_lane[0], 0, N - 1)
    ].set(keep_t[0])

    if resolve("CAUSE_TPU_FPHASE") == "pallas" and N % 128 == 0:
        # fused tile-window expansion (pallas_fphase): no scatters, no
        # cumsums — per-tile compare-select windows in VMEM compute
        # the fills and coverage; visibility is a vectorized second
        # pass in the same kernel
        from .pallas_fphase import fphase_expand

        cov_start = jnp.where(seg_cov, sg_lane0, N).astype(jnp.int32)
        cov_end = jnp.where(
            seg_cov, sg_lane0 + sg_len, 0).astype(jnp.int32)
        cs, ce = sort_pairs((cov_start, cov_end), num_keys=1)
        killed_ext = killed_sc[:N] | root_lane
        flags = (valid.astype(jnp.int32)
                 | (killed_ext.astype(jnp.int32) << 1))
        rank_lane, visible = fphase_expand(
            lk, tb_l, cs, ce, vclass, seg, flags)
        overflow = overflow_u | overflow_k
        return rank_lane, visible, conflict, overflow

    tl_l = jnp.where(lk < N, lk, 0)
    ok_l = lk < N
    d_base = jnp.where(
        ok_l,
        tb_l - jnp.concatenate([jnp.zeros((1,), jnp.int32), tb_l[:-1]]),
        0,
    )
    d_lane = jnp.where(
        ok_l,
        tl_l - jnp.concatenate([jnp.zeros((1,), jnp.int32), tl_l[:-1]]),
        0,
    )
    # kept tokens sit in the sorted prefix with strictly increasing
    # lanes; the rest dump into spread slots past N — sorted + unique
    # by construction (annotated under CAUSE_TPU_SCATTER=hint)
    scat = jnp.where(ok_l, tl_l, N + uidx)
    su_kw = _hint_kw(sorted_=True, unique=True)
    bits = (N - 1).bit_length()
    if 2 * bits <= 30:
        # base and lane are both < N, so their delta streams pack into
        # one int32 place-value pair: ONE scatter + ONE cumsum instead
        # of two of each (deltas may be negative, but the cumsum is
        # exact and every prefix total is a valid packed (base, lane))
        d_pack = d_base * (1 << bits) + d_lane
        pack_n = jnp.zeros(N + U, jnp.int32).at[scat].add(
            d_pack, **su_kw)[:N]
        pack_fill = jnp.cumsum(pack_n)
        base_fill = pack_fill >> bits
        lane_fill = pack_fill & ((1 << bits) - 1)
    else:  # concat width N > 32k (per-tree capacity > 16k): packed
           # pairs would overflow int32
        base_n = jnp.zeros(N + U, jnp.int32).at[scat].add(
            d_base, **su_kw)[:N]
        lane_n = jnp.zeros(N + U, jnp.int32).at[scat].add(
            d_lane, **su_kw)[:N]
        base_fill = jnp.cumsum(base_n)
        lane_fill = jnp.cumsum(lane_n)
    has_tok = jnp.zeros(N + U, bool).at[scat].set(True, **su_kw)[:N]
    lane_idx = jnp.arange(N, dtype=jnp.int32)

    # per-lane coverage flags from the segment tables (marshal order =
    # ascending lane order): covered = lane belongs to a token that is
    # kept, either via its own token (exploded) or its segment's token
    # spread dump slots past N keep both index streams unique (segment
    # starts/ends are distinct for live segments: disjoint ascending)
    cov_cnt = jnp.zeros(N + 1 + S, jnp.int32)
    cov_cnt = cov_cnt.at[
        jnp.where(seg_cov, sg_lane0, N + 1 + sidx)
    ].add(1, **uniq)
    cov_cnt = cov_cnt.at[
        jnp.where(seg_cov, sg_lane0 + sg_len, N + 1 + sidx)
    ].add(-1, **uniq)
    in_surviving = jnp.cumsum(cov_cnt[:N]) > 0

    # surviving-segment lanes take the seg token's base + offset (their
    # own has_tok is only set at the head lane — the fill carries it);
    # exploded lanes have their own token scatter; everything else
    # (padding, dropped twins, duplicate tokens) ranks at N
    rank_lane = jnp.where(
        valid & (in_surviving | has_tok),
        base_fill + (lane_idx - lane_fill),
        N,
    ).astype(jnp.int32)

    # visibility
    hideish_l = (vclass == VCLASS_HIDE) | (vclass == VCLASS_H_HIDE)
    nxt_same_seg = jnp.concatenate([
        (seg[1:] == seg[:N - 1]) & (seg[:N - 1] >= 0),
        jnp.zeros((1,), bool),
    ])
    nxt_hide = jnp.concatenate([hideish_l[1:], jnp.zeros((1,), bool)])
    kill_in_seg = in_surviving & nxt_same_seg & nxt_hide
    killed = killed_sc[:N] | kill_in_seg

    visible = (
        valid & (rank_lane < N) & (vclass == 0) & ~root_lane & ~killed
    )
    overflow = overflow_u | overflow_k
    return rank_lane, visible, conflict, overflow


def merge_weave_kernel_v5(hi, lo, cci, vclass, valid, seg,
                          sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                          sg_len, sg_lane0, sg_dense, sg_tail_special,
                          sg_valid, sg_vsum, u_max: int, k_max: int,
                          stage: str | None = None,
                          euler: str = "doubling"):
    """The v5 segment-union kernel (see ``_merge_weave_kernel_v5_impl``
    for the phase-by-phase contract), traced under an obs span so a
    bench/harvest trace attributes host TRACE time — where the sort/
    gather/search strategy spans nest — to the kernel build it
    belongs to. Runs only at trace time (the body is jit-staged), so
    the span cost never touches the dispatch path."""
    from ..obs import span as _span

    with _span("weave.trace.v5", n=int(hi.shape[-1]),
               u_max=int(u_max), k_max=int(k_max),
               stage=stage or "FULL", euler=euler):
        return _merge_weave_kernel_v5_impl(
            hi, lo, cci, vclass, valid, seg,
            sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
            sg_len, sg_lane0, sg_dense, sg_tail_special,
            sg_valid, sg_vsum, u_max=u_max, k_max=k_max,
            stage=stage, euler=euler)


merge_weave_kernel_v5_jit = jax.jit(
    merge_weave_kernel_v5,
    static_argnames=("u_max", "k_max", "stage", "euler"),
)


@partial(jax.jit, static_argnames=("u_max", "k_max", "euler"))
def batched_merge_weave_v5(hi, lo, cci, vclass, valid, seg,
                           sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                           sg_len, sg_lane0, sg_dense, sg_tail_special,
                           sg_valid, sg_vsum, u_max: int, k_max: int,
                           euler: str = "doubling"):
    """Segment-union batch: [B, N] node lanes + [B, S] segment tables
    -> per-replica (rank, visible, conflict, overflow), rank/visible
    indexed by concat lane. ``euler="walk"`` ranks the contracted
    forest with the sequential Pallas traversal (pallas_ops.euler_walk)
    instead of log-depth pointer doubling."""

    def row(*a):
        return merge_weave_kernel_v5(*a, u_max=u_max, k_max=k_max,
                                     euler=euler)

    return jax.vmap(row)(hi, lo, cci, vclass, valid, seg,
                         sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                         sg_len, sg_lane0, sg_dense, sg_tail_special,
                         sg_valid, sg_vsum)
