"""The v4 merge+weave kernel: marshal-resolved causes, no device search.

TPU profiling of v3 (PERF.md, scripts/probe_stage1.py) showed the
remaining cost concentrated in exactly the places where the kernel
re-derives information the HOST already had at marshal time:

- the 15-round (hi, lo) binary search resolving irregular causes
  (~1.4 s at 1024x20k) re-discovers, per merge, which lane each cause
  id lives at — but every input tree already knows its own causes
  (``NodeArrays.cause_idx`` is computed once per tree, and insert
  validates cause-must-exist, so intra-tree resolution never fails);
- the 6-array sort-permutation moves the cause id lanes (chi, clo)
  through HBM only to feed that search.

v4 therefore changes the device contract: instead of cause *ids*, each
lane carries ``cci`` — the index of its cause in the **concatenated
pre-sort lane array** (tree offset + within-tree cause index, free at
marshal time; -1 for the root / padding). On device, cause resolution
collapses to two data movements, both O(N):

1. one id sort carrying ``(iota, vclass, cci)`` payloads — the iota
   payload IS the sort permutation ``order``;
2. ``concat2head``: scatter each sorted lane's *kept-head* position to
   its concat slot (``.at[order].set(khead)``) — the inverse
   permutation composed with duplicate-collapse in a single scatter —
   then ``cause_pos = concat2head[cci]``, one gather.

``khead`` (last non-duplicate lane at-or-before each sorted position)
redirects a cause that resolved to a *dropped duplicate* copy of a node
to the kept copy, which is what makes the trick sound for K-ary unions:
duplicate lanes are key-equal and adjacent after the sort.

Everything downstream (chain runs, contracted Euler ranking,
delta-cumsum rank expansion, direction-flipped visibility) matches
``jaxw3.merge_weave_kernel_v3`` — with the host-jump walk stepping
through ``cause_pos`` directly (a special's parent IS its cause,
shared.cljc:225-241 semantics via jaxw.linearize's derived tree T*).
Run-budget ``k_max`` + overflow flag behave exactly like v2/v3; the
pure weaver remains the oracle and v1 the device reference
(tests/test_jax_v4.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .arrays import I32_MAX, VCLASS_H_HIDE, VCLASS_HIDE
from .jaxw import _euler_rank, _link_children
from .jaxw3 import _shift1

__all__ = [
    "merge_weave_kernel_v4",
    "batched_merge_weave_v4",
]


def merge_weave_kernel_v4(hi, lo, cci, vclass, valid, k_max: int,
                          euler: str = "doubling"):
    """Union + reweave for one replica set, marshal-resolved causes.

    Inputs are the concatenated lanes of any number of trees, each
    individually in ascending id order: ``hi``/``lo`` int32 id lanes
    (invalid lanes MUST carry I32_MAX in both — ``NodeArrays.id_lanes``
    and ``benchgen`` guarantee it), ``cci`` the concat index of each
    lane's cause (-1 for root/none/padding), ``vclass``, ``valid``.
    Returns ``(order, rank, visible, conflict, overflow)`` exactly like
    ``jaxw3.merge_weave_kernel_v3``. ``euler`` picks the contracted
    ranking backend: "doubling" (XLA pointer doubling) or "walk" (the
    sequential Pallas traversal, ``pallas_ops.euler_walk``).
    """
    N = hi.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    targets = jnp.arange(1, k_max + 1, dtype=jnp.int32)

    # ---- union: one 2-key sort carrying (order, vclass, cci) payloads
    hi = jnp.where(valid, hi, I32_MAX)
    lo = jnp.where(valid, lo, I32_MAX)
    h, l, order, vc, cci_s = lax.sort(
        (hi, lo, idx, vclass, cci.astype(jnp.int32)), num_keys=2
    )
    va = ~((h == I32_MAX) & (l == I32_MAX))

    prev_h, prev_l = _shift1(h, -1), _shift1(l, -1)
    dup = (h == prev_h) & (l == prev_l) & (idx > 0) & va
    keep = va & ~dup

    # ---- cause resolution: concat slot -> kept head of the sorted
    # duplicate group, one scatter + one gather
    khead = lax.cummax(jnp.where(keep, idx, -1))
    concat2head = jnp.zeros(N, jnp.int32).at[order].set(khead)
    cp = concat2head[jnp.clip(cci_s, 0, N - 1)]
    cause_pos = jnp.where(va & (cci_s >= 0), cp, 0).astype(jnp.int32)

    # duplicate lanes must agree on body (cause + value class); equal
    # cause ids resolve to equal kept heads, so positions compare ids
    conflict = jnp.any(
        dup & (
            (vc != _shift1(vc, 0)) | (cause_pos != _shift1(cause_pos, 0))
        )
    )

    cum_keep = jnp.cumsum(keep.astype(jnp.int32))
    kidx = cum_keep - 1
    n_kept = cum_keep[-1]
    is_root = keep & (idx == 0)
    special = keep & (vc > 0)
    rel = keep & ~is_root

    sp_pack = lax.cummax(
        jnp.where(keep, idx * 2 + special.astype(jnp.int32), -1)
    )
    sp_prev = _shift1(sp_pack, -1)
    prev_kept = jnp.where(sp_prev >= 0, sp_prev >> 1, -1)
    prev_kept_special = (sp_prev >= 0) & (sp_prev % 2 == 1)

    # adjacency: my cause IS the previous kept node (v3 compared raw
    # shifted ids; duplicate lanes carry the head's key so the two
    # formulations agree)
    adj = rel & (cause_pos == prev_kept) & (prev_kept >= 0)
    host_case = adj & ~special & prev_kept_special
    irregular = rel & (~adj | host_case)

    # ---- compact irregular lanes into K slots
    ir_cum = jnp.cumsum(irregular.astype(jnp.int32))
    n_irr = ir_cum[-1]
    q_lane = jnp.searchsorted(ir_cum, targets, side="left").astype(jnp.int32)
    q_valid = targets <= jnp.minimum(n_irr, k_max)
    q_c = jnp.clip(q_lane, 0, N - 1)
    q_special = special[q_c]
    q_cause = cause_pos[q_c]

    # ---- host jump at K: a special's parent is its cause, so the
    # first-non-special-ancestor walk steps through cause_pos itself
    def wcond(c):
        p, i = c
        ps = jnp.clip(p, 0, N - 1)
        return (i < N) & jnp.any(q_valid & ~q_special & special[ps])

    def wbody(c):
        p, i = c
        ps = jnp.clip(p, 0, N - 1)
        step = q_valid & ~q_special & special[ps]
        return jnp.where(step, cause_pos[ps], p), i + 1

    host_q, _ = lax.while_loop(wcond, wbody, (q_cause, jnp.int32(0)))
    q_parent = jnp.where(q_special, q_cause, host_q)

    # ---- glue: an adjacent child only glues if its parent has no
    # other (irregular) children (v3 refinement: any node with external
    # children is a run tail, so child runs attach after whole runs)
    extra = jnp.zeros(N, jnp.int32).at[
        jnp.where(q_valid, q_parent, N)
    ].add(1, mode="drop")
    ec_pack = lax.cummax(
        jnp.where(keep, idx * 2 + (extra > 0).astype(jnp.int32), -1)
    )
    ec_prev = _shift1(ec_pack, -1)
    prev_kept_contested = (ec_prev >= 0) & (ec_prev % 2 == 1)
    glued = adj & ~host_case & ~prev_kept_contested

    run_start = keep & ~glued
    rs_cum = jnp.cumsum(run_start.astype(jnp.int32))
    run_id = rs_cum - 1
    n_runs = rs_cum[-1]
    overflow = n_runs > k_max

    # ---- compact run heads into K slots
    head_lane = jnp.searchsorted(rs_cum, targets, side="left").astype(
        jnp.int32
    )
    r_valid = targets <= jnp.minimum(n_runs, k_max)
    head_c = jnp.clip(head_lane, 0, N - 1)

    parent_full = jnp.full(N, -1, jnp.int32).at[
        jnp.where(q_valid, q_lane, N)
    ].set(q_parent, mode="drop")
    h_parent_lane = jnp.where(
        irregular[head_c], parent_full[head_c],
        jnp.where(adj[head_c], prev_kept[head_c], -1),
    )
    h_parent_lane = jnp.where(r_valid & ~is_root[head_c], h_parent_lane, -1)
    parent_run = jnp.where(
        h_parent_lane >= 0,
        run_id[jnp.clip(h_parent_lane, 0, N - 1)],
        -1,
    ).astype(jnp.int32)

    h_special = special[head_c]
    h_kidx = kidx[head_c]
    nxt_kidx = jnp.concatenate([h_kidx[1:], h_kidx[:1]])  # filler tail
    run_len = jnp.where(
        r_valid,
        jnp.where(targets == n_runs, n_kept - h_kidx, nxt_kidx - h_kidx),
        0,
    ).astype(jnp.int32)

    # ---- contracted sibling sort + Euler ranking, all at K
    parent_sort = jnp.where(r_valid & (parent_run >= 0), parent_run, k_max)
    packed = parent_sort * 2 + (~h_special).astype(jnp.int32)
    sord = jnp.lexsort((-head_c, packed))
    fc, ns = _link_children(sord, parent_sort)
    parent_up = jnp.where(r_valid & (parent_run >= 0), parent_run, -1)
    if euler == "walk":
        from .pallas_ops import euler_walk

        base = euler_walk(fc, ns, parent_up, run_len, k_max)
    else:
        base, _ = _euler_rank(fc, ns, parent_up, run_len)

    # ---- expansion: per-run bases -> deltas -> one cumsum
    delta = jnp.where(
        r_valid, base - jnp.concatenate([jnp.zeros((1,), base.dtype),
                                         base[:-1]]), 0
    )
    delta_n = jnp.zeros(N, jnp.int32).at[
        jnp.where(r_valid, head_c, N)
    ].set(delta.astype(jnp.int32), mode="drop")
    base_ff = jnp.cumsum(delta_n)
    ffh = lax.cummax(jnp.where(run_start, kidx, -1))
    rank = jnp.where(keep, base_ff + (kidx - ffh), N).astype(jnp.int32)

    # ---- visibility. in-run: next kept lane is a glued hide (its
    # cause IS me) — reversed forward-fill, elementwise
    hideish = (vc == VCLASS_HIDE) | (vc == VCLASS_H_HIDE)
    kg = glued & hideish
    rpack = lax.cummax(
        jnp.where(jnp.flip(keep), idx * 2 + jnp.flip(kg).astype(jnp.int32),
                  -1)
    )
    rprev = _shift1(rpack, -1)
    killed_inrun = jnp.flip((rprev >= 0) & (rprev % 2 == 1))

    # run tails: the preorder-successor run's head may hide me (K-wide)
    run_by_pos = jnp.full(N, -1, jnp.int32).at[
        jnp.where(r_valid, jnp.clip(base, 0, N - 1), N)
    ].set(jnp.arange(k_max, dtype=jnp.int32), mode="drop")
    succ_pos = base + run_len
    succ_run = jnp.where(
        r_valid & (succ_pos < n_kept),
        run_by_pos[jnp.clip(succ_pos, 0, N - 1)],
        -1,
    )
    s_c = jnp.clip(
        jnp.where(succ_run >= 0, head_c[jnp.clip(succ_run, 0, k_max - 1)],
                  0),
        0, N - 1,
    )
    s_is_hide = (succ_run >= 0) & (
        (vc[s_c] == VCLASS_HIDE) | (vc[s_c] == VCLASS_H_HIDE)
    )
    # tail of run r = the kept lane before the NEXT run's head; last
    # run's tail is the last kept lane overall. Cause ids compare as
    # kept-head positions, so "succ head hides the tail" is one compare
    nxt_head = jnp.concatenate([head_c[1:], head_c[:1]])
    tail_lane = jnp.where(
        targets == n_runs,
        jnp.maximum(sp_pack[-1] >> 1, 0),
        prev_kept[jnp.clip(nxt_head, 0, N - 1)],
    ).astype(jnp.int32)
    t_c = jnp.clip(tail_lane, 0, N - 1)
    kill_tail = r_valid & s_is_hide & (cause_pos[s_c] == t_c)
    killed_tail = jnp.zeros(N, bool).at[
        jnp.where(kill_tail, t_c, N)
    ].set(True, mode="drop")

    visible = (
        keep & (vc == 0) & ~is_root & ~(killed_inrun | killed_tail)
    )
    return order, rank, visible, conflict, overflow


merge_weave_kernel_v4_jit = jax.jit(
    merge_weave_kernel_v4, static_argnames=("k_max", "euler")
)


@partial(jax.jit, static_argnames=("k_max", "euler"))
def batched_merge_weave_v4(hi, lo, cci, vclass, valid, k_max: int,
                           euler: str = "doubling"):
    """Marshal-resolved batch: [B, M] lanes -> per-replica weave ranks.
    Same output contract as ``jaxw3.batched_merge_weave_v3``; inputs
    swap the cause id lanes (chi, clo) for the single ``cci`` lane.
    ``euler="walk"`` ranks the contracted trees with the sequential
    Pallas traversal (its grid absorbs the vmap batch dimension)."""

    def row(h, l, cc, vc, va):
        return merge_weave_kernel_v4(h, l, cc, vc, va, k_max, euler=euler)

    return jax.vmap(row)(hi, lo, cci, vclass, valid)
