"""Static bitonic sorting network — a TPU-shaped ``lax.sort``.

XLA's TPU sort lowers a variadic comparator loop whose constants the
round-2 microbenches showed dominating kernel phases; a bitonic
network is log^2(n) *elementwise* compare-exchange stages (reshape +
min/max/select only), which the VPU streams at full width across any
leading batch dimensions — no comparator calls, no data-dependent
control flow, fully fusable. At the weave kernels' token widths
(~2k-4k lanes) that is ~78 static stages.

Semantics: ``bitonic_sort(operands, num_keys)`` sorts along the LAST
axis, ascending and lexicographic over the first ``num_keys``
operands; remaining operands ride as payloads (same contract as
``lax.sort``). Unlike ``lax.sort`` the network is not stable, so the
original position is appended as an implicit final key — the result
is the unique fully-deterministic stable order, for every input
(including duplicate keys).

``sort_pairs`` is the drop-in the kernels use; it dispatches to
``lax.sort`` unless ``CAUSE_TPU_SORT=bitonic`` (read at trace time),
so hardware A/B needs no code change.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["bitonic_sort", "sort_pairs"]

_I32_MAX = jnp.iinfo(jnp.int32).max


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _lex_lt(lo_keys, hi_keys):
    """Elementwise lexicographic lo < hi over aligned key lists."""
    lt = None
    eq = None
    for a, b in zip(lo_keys, hi_keys):
        this_lt = a < b
        this_eq = a == b
        if lt is None:
            lt, eq = this_lt, this_eq
        else:
            lt = lt | (eq & this_lt)
            eq = eq & this_eq
    return lt


def bitonic_sort(operands, num_keys: int = 1):
    """Sort int32 arrays along the last axis (see module docstring).

    Returns the operands tuple in the same order, sorted. Keys must be
    int32-comparable; padding uses int32 max so every real key must be
    strictly below it (true for all kernel lanes, which reserve
    ``I32_MAX`` as the invalid sentinel — those sort last, exactly as
    with ``lax.sort``)."""
    operands = tuple(operands)
    n = operands[0].shape[-1]
    p = _next_pow2(n)
    lead = operands[0].shape[:-1]
    iota = jnp.broadcast_to(
        jnp.arange(p, dtype=jnp.int32), lead + (p,)
    )
    arrs = []
    for i, x in enumerate(operands):
        if p != n:
            fill = _I32_MAX if i < num_keys else 0
            pad = jnp.full(lead + (p - n,), fill, x.dtype)
            x = jnp.concatenate([x, pad], axis=-1)
        arrs.append(x)
    arrs.append(iota)  # implicit final key: deterministic stability
    key_pos = list(range(num_keys)) + [len(arrs) - 1]

    k = 2
    while k <= p:
        j = k // 2
        while j >= 1:
            nb = p // (2 * j)
            asc = ((jnp.arange(nb, dtype=jnp.int32) * 2 * j) & k) == 0
            asc = asc[:, None]  # [nb, 1] broadcasts over the j axis
            rs = [x.reshape(lead + (nb, 2, j)) for x in arrs]
            lo = [x[..., 0, :] for x in rs]
            hi = [x[..., 1, :] for x in rs]
            lt = _lex_lt([lo[i] for i in key_pos],
                         [hi[i] for i in key_pos])
            keep = jnp.where(asc, lt, ~lt)
            out = []
            for a, b in zip(lo, hi):
                first = jnp.where(keep, a, b)
                second = jnp.where(keep, b, a)
                out.append(
                    jnp.stack([first, second], axis=-2).reshape(
                        lead + (p,)
                    )
                )
            arrs = out
            j //= 2
        k *= 2

    arrs = arrs[:-1]  # drop the iota key
    if p != n:
        arrs = [x[..., :n] for x in arrs]
    return tuple(arrs)


def sort_pairs(operands, num_keys: int = 1):
    """The kernels' sort: ``lax.sort`` by default; trace-time switch
    ``CAUSE_TPU_SORT`` selects ``bitonic`` (the XLA-level network —
    elementwise stages, but each round-trips HBM), ``pallas`` (the
    same network VMEM-resident inside one Pallas kernel per 8-row
    block — one HBM read + write per operand total; needs a Mosaic
    -capable backend) or ``matrix`` (blocked O(n^2) rank counting +
    rowgather apply — pure-XLA streaming, weaver/matsort.py) for
    hardware A/B with no code change."""
    from ..obs import span
    from ..switches import resolve

    mode = resolve("CAUSE_TPU_SORT")
    with span("weave.sort", strategy=mode or "xla",
              width=int(operands[0].shape[-1]), n_ops=len(operands)):
        if mode == "bitonic":
            return bitonic_sort(operands, num_keys=num_keys)
        if mode == "pallas":
            from .pallas_sort import pallas_bitonic_sort

            return pallas_bitonic_sort(operands, num_keys=num_keys)
        if mode == "matrix":
            from .matsort import matrix_sort

            return matrix_sort(operands, num_keys=num_keys)
        return lax.sort(tuple(operands), num_keys=num_keys)
