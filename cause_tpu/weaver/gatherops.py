"""Switchable 1-D gather strategies for the weave kernels.

TPU has no hardware gather: XLA lowers ``table[idx]`` to per-element
HBM transactions (~14 ns/element on the round-2 microbenches — the
single most expensive primitive in the kernel ladder). The
``rowgather`` strategy instead fetches whole 128-lane rows with
``take_along_axis`` (a supported fast path) and contracts with a
one-hot lane mask — 128x data amplification, but every byte streams.
Which wins depends on the query:table ratio and the backend;
``CAUSE_TPU_GATHER=rowgather`` flips the kernels at trace time so the
hardware A/B needs no code change (same discipline as
``bitonic.sort_pairs``).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["take1d", "rowgather1d", "searchsorted_iota_right",
           "searchsorted_targets_left"]

_LANE = 128
_LANE_SHIFT = _LANE.bit_length() - 1


def rowgather1d(table, idx):
    """``table[idx]`` along the last axis via 128-wide row fetch +
    one-hot contraction. Tables whose last axis is not a multiple of
    128 (the token/segment tables) are zero-padded up — ``idx`` must be
    in-range (callers clip, as they already must for XLA gathers), so
    the padding is never read."""
    lead = table.shape[:-1]
    n = table.shape[-1]
    if n % _LANE:
        table = jnp.concatenate([
            table,
            jnp.zeros(lead + (_LANE - n % _LANE,), table.dtype),
        ], axis=-1)
        n = table.shape[-1]
    q = idx.shape[-1]
    rows = table.reshape(lead + (n // _LANE, _LANE))
    fetched = jnp.take_along_axis(
        rows, (idx >> _LANE_SHIFT)[..., None], axis=-2
    )  # [..., q, 128]
    onehot = (
        lax.broadcasted_iota(jnp.int32, idx.shape + (_LANE,),
                             len(idx.shape))
        == (idx & (_LANE - 1))[..., None]
    )
    return jnp.sum(
        jnp.where(onehot, fetched, 0), axis=-1
    ).astype(table.dtype)


def take1d(table, idx):
    """The kernels' gather from a full-width lane table: plain XLA
    gather by default, ``rowgather1d`` when
    ``CAUSE_TPU_GATHER=rowgather`` (trace-time switch)."""
    from ..obs import span
    from ..switches import resolve

    mode = "rowgather" if resolve("CAUSE_TPU_GATHER") == "rowgather" \
        else "xla"
    with span("weave.gather", strategy=mode,
              width=int(table.shape[-1])):
        if mode == "rowgather":
            return rowgather1d(table, idx)
        return table[idx]


def searchsorted_iota_right(keys_cum, q: int):
    """``searchsorted(keys_cum, arange(q), side="right")`` for a
    NON-DECREASING ``keys_cum``.

    Default: histogram the keys (one scatter-add) and prefix-sum — no
    per-query binary search. But an XLA TPU scatter is random access
    just like a gather, so ``CAUSE_TPU_SEARCH=matrix`` (trace-time)
    switches to the O(n*q) comparison-matrix count — side="right"
    index = #{keys <= target} — which is pure elementwise work the VPU
    streams with zero random access (same trade as
    jaxw5._pair_search_le). NOTE: at token width the matrix is
    [q, n] ~ 5M/row; if XLA materializes it instead of fusing the
    reduction this form loses badly (47 s/op on CPU!), so the
    narrower ``matrix-table`` value applies matrix search only to the
    S-width table search in jaxw5 and leaves this histogram alone —
    that is what the combined beststream config uses until the
    microbench decides."""
    from ..obs import span
    from ..switches import resolve

    mode = "matrix" if resolve("CAUSE_TPU_SEARCH") == "matrix" \
        else "histogram"
    with span("weave.search", strategy=mode, site="iota_right",
              q=int(q)):
        if mode == "matrix":
            tgt = jnp.arange(q, dtype=keys_cum.dtype)
            le = keys_cum[None, :] <= tgt[:, None]
            return jnp.sum(le, axis=1).astype(jnp.int32)
        hist = jnp.zeros(q + 1, jnp.int32).at[
            jnp.clip(keys_cum, 0, q)
        ].add(1, mode="drop")
        return jnp.cumsum(hist[:q]).astype(jnp.int32)


def searchsorted_targets_left(keys_cum, k: int):
    """``searchsorted(keys_cum, arange(1, k + 1), side="left")`` for a
    NON-DECREASING ``keys_cum`` — streaming form. ``left`` with target
    t counts keys strictly below t, i.e. keys <= t-1 — the identical
    histogram prefix as the iota/right case with targets shifted one,
    so this IS that function under another contract."""
    return searchsorted_iota_right(keys_cum, k)
