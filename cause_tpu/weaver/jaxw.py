"""The JAX/TPU device weaver — the north-star kernel.

The reference computes a weave by scanning nodes one at a time through
``weave-node`` (shared.cljc:225-241, O(n) per insert, O(n^2) rebuild).
On TPU we compute the *whole* linearization at once from the bag of
nodes.

**Order semantics** (derived from ``weave-asap?``/``weave-later?``,
shared.cljc:194-223, and fuzz-verified against the pure weaver): the
weave equals a chronological replay — processing nodes in ascending id
order, a special node inserts immediately after its cause, and a
non-special node inserts immediately before the first *non-special*
node after its cause (i.e. it skips the whole run of specials sitting
there). Two facts make that replay parallel:

- no non-special ever lands *inside* a run of specials, so the
  specials attached (via special-only cause chains) to a common
  non-special **host** stay one contiguous block right after it, in
  an order of their own that never changes; and
- projected onto non-specials only, every node simply follows its
  host, so the projection is a plain RGA order.

Hence the weave is one preorder DFS of the derived tree T*:

- special  -> parent is its cause;
- non-special -> parent is its **host**: the first non-special node
  on its cause chain (one pointer-doubling jump over special causes);
- children sort specials-first, then descending id (so each node's
  special block precedes its non-special children).

The kernel is: the host pointer-jump, one ``lexsort`` to group
children under T* parents in sibling order (the radix-sort reification
of the predicates), an Euler tour over 2N edges, and pointer-doubling
list ranking (ceil(log2 2N) gather rounds). Visibility (``hide?``,
list.cljc:48-55) is one shifted compare on the final ranks. Everything
is static-shape and jit/vmap-friendly; ``merge_weave_kernel`` unions
two id-sorted node sets (packed-id sort + dedupe + searchsorted cause
resolution) and reweaves — turning the reference's O(n*m) sequential
merge (shared.cljc:300-314) into one data-parallel program, vmappable
across thousands of replicas.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .gatherops import take1d
from .arrays import (
    I32_MAX,
    NodeArrays,
    VCLASS_H_HIDE,
    VCLASS_HIDE,
)

__all__ = [
    "linearize",
    "linearize_v2",
    "estimate_runs",
    "weave_arrays",
    "refresh_list_weave",
    "refresh_map_weave",
    "merge_list_trees",
    "merge_map_trees",
    "merge_many_list_trees",
    "merge_weave_kernel",
    "merge_weave_kernel_v2",
    "batched_merge_weave",
    "batched_merge_weave_v2",
]


def _link_children(order, parent_sort):
    """Given lanes sorted into sibling order (``order``) and each lane's
    parent key, link the per-parent child lists: returns
    (first_child, next_sibling) as [N] lane-index arrays (-1 = none)."""
    N = parent_sort.shape[0]
    p = take1d(parent_sort, order)
    is_start = jnp.concatenate([jnp.ones((1,), bool), p[1:] != p[:-1]])
    same_parent_next = jnp.concatenate([p[1:] == p[:-1], jnp.zeros((1,), bool)])
    succ_in_sort = jnp.concatenate([order[1:], jnp.zeros((1,), order.dtype)])
    ns_sorted = jnp.where(same_parent_next, succ_in_sort, -1).astype(jnp.int32)
    next_sibling = jnp.zeros(N, jnp.int32).at[order].set(ns_sorted)
    ok_parent = (p >= 0) & (p < N)
    fc_target = jnp.where(is_start & ok_parent, p, N)
    first_child = (
        jnp.full(N + 1, -1, jnp.int32).at[fc_target].set(order.astype(jnp.int32))[:N]
    )
    return first_child, next_sibling


def _child_sort(parent_sort, special, hi, lo):
    """Group nodes under their parents in sibling order (specials first,
    then descending id — ids compare as their (hi, lo) lanes)."""
    not_special = (~special).astype(jnp.int32)
    order = jnp.lexsort((-lo, -hi, not_special, parent_sort))
    return _link_children(order, parent_sort)


def _euler_rank(first_child, next_sibling, parent_up, weights):
    """Weighted preorder rank + subtree weight via an Euler tour (2N
    edges: d(i)=i, u(i)=N+i) and pointer-doubling suffix sums. The rank
    of node i is the total weight strictly before d(i) in the tour;
    zero-weight nodes still occupy tour slots but displace nothing."""
    N = first_child.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    up = N + idx
    next_d = jnp.where(first_child >= 0, first_child, up)
    next_u = jnp.where(
        next_sibling >= 0,
        next_sibling,
        jnp.where(parent_up >= 0, N + parent_up, up),
    )
    nxt = jnp.concatenate([next_d, next_u])
    w = jnp.concatenate([weights.astype(jnp.int32), jnp.zeros(N, jnp.int32)])

    steps = max(1, math.ceil(math.log2(2 * N)))

    def body(_, carry):
        val, nx = carry
        return val + take1d(val, nx), take1d(nx, nx)

    val, _ = lax.fori_loop(0, steps, body, (w, nxt))
    s_down = val[:N]   # weight at-or-after d(i) in the tour
    s_up = val[N:]     # weight at-or-after u(i)
    total = jnp.sum(weights.astype(jnp.int32))
    rank = (total - s_down).astype(jnp.int32)
    size = (s_down - s_up).astype(jnp.int32)
    return rank, size


def _scatter_by_rank(rank, valid, N):
    """node_at[pos] lookup table (size N+2; unwritten slots are -1)."""
    idx = jnp.arange(N, dtype=jnp.int32)
    return (
        jnp.full(N + 2, -1, jnp.int32)
        .at[jnp.where(valid, rank, N + 1)]
        .set(idx)
    )


def linearize(hi, lo, cause_idx, vclass, valid):
    """Weave position + visibility for one tree's node lanes.

    ``hi``/``lo`` are the two int32 id lanes (see arrays.PackSpec).
    Lane 0 must be the root sentinel (sorted-id layout guarantees it: no
    real node id sorts below ``(0, "0", 0)``). Returns ``(rank,
    visible)``: rank is the weave position (invalid lanes get N).
    """
    N = hi.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    is_root = valid & (idx == 0)
    special = valid & (vclass > 0)

    # ---- host jump: first non-special ancestor through the cause chain
    # (pointer doubling over special causes; terminates at non-specials).
    cause_safe = jnp.clip(cause_idx, 0, N - 1)
    host = cause_safe
    for _ in range(max(1, math.ceil(math.log2(N)))):
        host = jnp.where(special[host], host[host], host)

    # ---- the derived tree T*: specials under their cause, non-specials
    # under their host; specials-first + descending-id sibling order.
    parent_t = jnp.where(special, cause_safe, host)
    parent_sort = jnp.where(valid & ~is_root, parent_t, N).astype(jnp.int32)
    fc, ns = _child_sort(parent_sort, special, hi, lo)
    parent_up = jnp.where(valid & ~is_root, parent_t, -1)
    rank, _size = _euler_rank(fc, ns, parent_up, valid.astype(jnp.int32))
    rank = jnp.where(valid, rank, N)

    # ---- visibility (hide?, list.cljc:48-55) via the weave successor.
    node_at = _scatter_by_rank(rank, valid, N)
    succ = node_at[jnp.clip(rank, 0, N) + 1]
    succ_safe = jnp.clip(succ, 0, N - 1)
    succ_is_hide = (
        (succ >= 0)
        & (
            (vclass[succ_safe] == VCLASS_HIDE)
            | (vclass[succ_safe] == VCLASS_H_HIDE)
        )
        & (cause_idx[succ_safe] == idx)
    )
    visible = valid & (vclass == 0) & ~is_root & ~succ_is_hide
    return rank, visible


_linearize_jit = jax.jit(linearize)


def _host_jump(special, cause_safe, rel, max_steps):
    """First non-special ancestor through the cause chain, by pointer
    doubling under a convergence-tested while_loop: real special chains
    are a few links deep (hide -> write; h.show -> hide), so this
    usually stops after one or two rounds instead of log2(N)."""

    def cond(c):
        host, i = c
        return (i < max_steps) & jnp.any(rel & special[host])

    def body(c):
        host, i = c
        return jnp.where(special[host], host[host], host), i + 1

    host, _ = lax.while_loop(cond, body, (cause_safe, jnp.int32(0)))
    return host


def linearize_v2(hi, lo, cause_idx, vclass, valid, k_max: int):
    """Chain-compressed weave linearization.

    Same inputs/outputs as ``linearize`` — plus an ``overflow`` flag —
    with one extra precondition: valid lanes must arrive in ascending
    id order (sibling order is derived from lane position instead of
    the hi/lo id lanes). Both in-tree callers guarantee it — the merge
    front half id-sorts, and ``NodeArrays.from_nodes_map`` builds lanes
    sorted; hand-built unsorted lanes must use ``linearize``. But
    the Euler-tour ranking (the gather-bound heart of v1) runs on a
    contracted tree: maximal lane-adjacent single-child chains of the
    derived tree T* collapse to one supernode each. Contraction needs
    only elementwise ops, scans and scatters (a chain is lane-adjacent
    precisely when a node's only T* child is the next lane, so run
    membership falls out of one cumsum/cummax), and preorder positions
    expand back as ``base[run] + offset-in-run``. Realistic causal
    trees are append-heavy — long typing runs, few conflict branch
    points — so K (number of runs) is typically orders of magnitude
    below N and the pointer-doubling cost collapses with it.

    ``k_max`` is the static capacity of the compressed tree. When the
    input has more than ``k_max`` runs the outputs are invalid and
    ``overflow`` is True: callers retry with a bigger bucket or fall
    back to plain ``linearize`` (kept for exactly that role).
    """
    N = hi.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    is_root = valid & (idx == 0)
    special = valid & (vclass > 0)
    rel = valid & ~is_root

    cause_safe = jnp.clip(cause_idx, 0, N - 1)
    host = _host_jump(special, cause_safe, rel, max(1, math.ceil(math.log2(N))))

    # ---- T* parents (lane-level, as v1)
    parent_t = jnp.where(special, cause_safe, host)
    parent = jnp.where(rel, parent_t, -1)

    # ---- chain contraction over *kept-lane* positions: dropped
    # duplicates and padding occupy lanes (the merge kernel interleaves
    # them with kept nodes), so adjacency is measured in the compacted
    # valid-lane numbering, not raw lane index.
    kidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    has_parent = parent >= 0
    pc = jnp.clip(parent, 0, N - 1)
    child_count = (
        jnp.zeros(N + 1, jnp.int32)
        .at[jnp.where(has_parent, pc, N)]
        .add(1)[:N]
    )
    only_child = has_parent & (child_count[pc] == 1)
    glued = only_child & (kidx[pc] == kidx - 1)  # adjacent among kept
    run_start = valid & ~glued
    run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1
    n_runs = jnp.sum(run_start.astype(jnp.int32))
    overflow = n_runs > k_max
    # offset within run, again in kept-lane numbering
    offset = kidx - lax.cummax(jnp.where(run_start, kidx, -1))

    # ---- compacted run arrays (slot k_max is the discard sentinel)
    rid_ok = run_start & (run_id < k_max)
    slot = jnp.where(rid_ok, run_id, k_max)
    head_lane = jnp.full(k_max + 1, -1, jnp.int32).at[slot].set(idx)[:k_max]
    head_special = (
        jnp.zeros(k_max + 1, bool).at[slot].set(special)[:k_max]
    )
    head_parent = jnp.full(k_max + 1, -1, jnp.int32).at[slot].set(parent)[:k_max]
    lane_ok = valid & (run_id < k_max) & (run_id >= 0)
    run_len = (
        jnp.zeros(k_max + 1, jnp.int32)
        .at[jnp.where(lane_ok, run_id, k_max)]
        .add(1)[:k_max]
    )
    valid_run = head_lane >= 0
    parent_run = jnp.where(
        head_parent >= 0,
        run_id[jnp.clip(head_parent, 0, N - 1)],
        -1,
    ).astype(jnp.int32)

    # ---- sibling sort over runs: 2 int32 keys (packed parent+class,
    # then descending head lane — lanes are id-sorted, so lane order is
    # id order)
    parent_sort = jnp.where(valid_run & (parent_run >= 0), parent_run, k_max)
    packed = parent_sort * 2 + (~head_special).astype(jnp.int32)
    order = jnp.lexsort((-head_lane, packed))
    fc, ns = _link_children(order, parent_sort)
    parent_up = jnp.where(valid_run & (parent_run >= 0), parent_run, -1)
    base, _ = _euler_rank(
        fc, ns, parent_up, jnp.where(valid_run, run_len, 0)
    )

    # ---- expand: every run's lanes are contiguous in the preorder
    rank = jnp.where(
        valid, base[jnp.clip(run_id, 0, k_max - 1)] + offset, N
    ).astype(jnp.int32)

    # ---- visibility (identical to v1)
    node_at = _scatter_by_rank(rank, valid, N)
    succ = node_at[jnp.clip(rank, 0, N) + 1]
    succ_safe = jnp.clip(succ, 0, N - 1)
    succ_is_hide = (
        (succ >= 0)
        & (
            (vclass[succ_safe] == VCLASS_HIDE)
            | (vclass[succ_safe] == VCLASS_H_HIDE)
        )
        & (cause_idx[succ_safe] == idx)
    )
    visible = valid & (vclass == 0) & ~is_root & ~succ_is_hide
    return rank, visible, overflow


_linearize_v2_jit = jax.jit(linearize_v2, static_argnames="k_max")


def linearize_map_forest(cause_idx, key_rank, vclass, valid, n_keys,
                         k_cap: int):
    """Map-weave ordering on device: one forest preorder over per-key
    mini list-weaves (map.cljc:21-45).

    Lanes are the real nodes in ascending id order; ``k_cap`` static
    slots of virtual key roots (lane N+k is key k's ROOT sentinel,
    ``n_keys`` of them live) are appended internally. Key-caused lanes
    hang off their key's root; id-caused lanes off their target — then
    the standard T* derivation applies per component.

    Returns ``s_down`` ([N], int32): the tour suffix weight of each real
    lane. Within one key's component s_down strictly decreases along
    weave order, so the host orders each key's nodes by descending
    s_down; cross-component offsets are irrelevant because the weave is
    a per-key dict.
    """
    N = cause_idx.shape[0]
    M = N + k_cap
    idx = jnp.arange(M, dtype=jnp.int32)
    is_rootlane = idx >= N
    valid_all = jnp.concatenate(
        [valid, jnp.arange(k_cap, dtype=jnp.int32) < n_keys]
    )
    special = jnp.concatenate([valid & (vclass > 0), jnp.zeros(k_cap, bool)])
    cause_all = jnp.concatenate(
        [
            jnp.where(key_rank >= 0, N + key_rank,
                      jnp.clip(cause_idx, 0, N - 1)),
            jnp.arange(N, M, dtype=jnp.int32),  # roots cause themselves
        ]
    )
    rel = valid_all & ~is_rootlane
    host = _host_jump(special, cause_all, rel,
                      max(1, math.ceil(math.log2(M))))
    parent_t = jnp.where(special, cause_all, host)
    parent_sort = jnp.where(rel, parent_t, M).astype(jnp.int32)
    # sibling order: specials first, then descending id == descending
    # lane (real lanes are id-sorted; roots are parentless)
    packed = parent_sort * 2 + (~special).astype(jnp.int32)
    order = jnp.lexsort((-idx, packed))
    fc, ns = _link_children(order, parent_sort)
    parent_up = jnp.where(rel, parent_t, -1)
    weights = jnp.where(valid_all & ~is_rootlane, 1, 0).astype(jnp.int32)
    _rank, _size = _euler_rank(fc, ns, parent_up, weights)
    # recover per-lane suffix weight: _euler_rank's rank = total - s_down
    total = jnp.sum(weights)
    s_down = total - _rank
    return s_down[:N]


_linearize_map_jit = jax.jit(linearize_map_forest, static_argnames="k_cap")


def refresh_map_weave(ct):
    """Full map-weave rebuild on device (the ``weaver="jax"`` path of
    cmap.weave): marshal with the shared map_lanes, rank the forest on
    device, and split the order back into the per-key weave dict —
    identical to the pure per-key replay (falls back to it off-domain).
    """
    from ..collections import cmap as c_map
    from .arrays import OutsideDomain, next_pow2, rebuild_map_weave

    try:
        nodes, cause_idx, key_rank, vclass, valid_n, keys = _padded_map_lanes(
            ct.nodes
        )
    except OutsideDomain:
        return c_map.weave(ct.evolve(weaver="pure")).evolve(weaver=ct.weaver)
    if not nodes:
        return ct.evolve(weave={})
    k_cap = next_pow2(max(1, len(keys)))
    s_down = np.asarray(
        _linearize_map_jit(
            jnp.asarray(cause_idx), jnp.asarray(key_rank),
            jnp.asarray(vclass), jnp.asarray(valid_n), len(keys),
            k_cap=k_cap,
        )
    )
    n = len(nodes)
    # resolve each lane's key ordinal host-side (single-level rule)
    key_of = np.where(key_rank[:n] >= 0, key_rank[:n], -1)
    for i in range(n):
        if key_of[i] < 0:
            key_of[i] = key_of[cause_idx[i]]
    order = sorted(range(n), key=lambda i: (key_of[i], -s_down[i]))
    return ct.evolve(weave=rebuild_map_weave(nodes, key_of, order, keys))


def _padded_map_lanes(nodes_map):
    """map_lanes padded to a power-of-two capacity with a valid mask."""
    from .arrays import map_lanes, next_pow2

    nodes, cause_idx, key_rank, vclass, keys = map_lanes(nodes_map)
    n = len(nodes)
    cap = next_pow2(max(1, n))
    pad = cap - n
    cause_idx = np.concatenate([cause_idx, np.full(pad, -1, np.int32)])
    key_rank = np.concatenate([key_rank, np.full(pad, -1, np.int32)])
    vclass = np.concatenate([vclass, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    return nodes, cause_idx, key_rank, vclass, valid, keys


def estimate_runs(cause_idx, vclass, valid) -> int:
    """Host-side (numpy) count of the chain-contracted tree's runs —
    the same contraction ``linearize_v2`` performs, so the device
    kernel can be chosen before dispatch instead of retrying after an
    overflow."""
    cause_idx = np.asarray(cause_idx)
    vclass = np.asarray(vclass)
    valid = np.asarray(valid)
    n = cause_idx.shape[0]
    idx = np.arange(n, dtype=np.int32)
    is_root = valid & (idx == 0)
    special = valid & (vclass > 0)
    rel = valid & ~is_root
    cause_safe = np.clip(cause_idx, 0, n - 1)
    host = cause_safe.copy()
    for _ in range(max(1, math.ceil(math.log2(max(2, n))))):
        on_special = special[host] & rel
        if not on_special.any():
            break
        host = np.where(on_special, host[host], host)
    parent = np.where(rel, np.where(special, cause_safe, host), -1)
    kidx = np.cumsum(valid.astype(np.int32)) - 1
    has_parent = parent >= 0
    pc = np.clip(parent, 0, n - 1)
    child_count = np.bincount(pc[has_parent], minlength=n)
    only_child = has_parent & (child_count[pc] == 1)
    glued = only_child & (kidx[pc] == kidx - 1)
    return int((valid & ~glued).sum())


def _run_budget(capacity: int) -> int:
    return max(16, capacity // 8)


def weave_arrays(na: NodeArrays, segs=None) -> Tuple[np.ndarray, np.ndarray]:
    """Run the device linearization for one tree; returns host-side
    ``(rank, visible)`` numpy arrays. Prefers the v5 segment-union
    kernel — a single tree never explodes a segment, so device work
    collapses to segment scale plus a few full-width scans — then the
    v4 merge kernel (marshal-resolved causes at full width), then the
    chain-compressed v2 and the uncompressed v1 (budget estimates are
    host-side, so a branchy tree never pays for a doomed dispatch).
    ``segs`` may carry a precomputed ``tree_segments`` table (the lane
    cache memoizes them per view)."""
    from .jaxw4 import merge_weave_kernel_v4_jit
    from .jaxw5 import merge_weave_kernel_v5_jit
    from .segments import SEG_LANE_KEYS, concat_segments, tree_segments

    hi, lo = na.id_lanes()
    k_max = _run_budget(na.capacity)
    if segs is None:
        segs = tree_segments(hi, lo, na.cause_idx, na.vclass, na.n)
    n_segs = segs["sg_len"].shape[0]
    if n_segs <= max(16, na.capacity // 4):
        # capacity-derived budget (NOT n_segs-derived): one compile per
        # capacity tier, like the v4/v2 paths — a per-count s_max would
        # retrace the kernel every time an edit crosses a table size
        s_max = max(16, na.capacity // 4)
        tables = concat_segments([(segs, na.n)], na.capacity, s_max)
        u_max = s_max + 8
        rank, visible, _, overflow = merge_weave_kernel_v5_jit(
            jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(na.cause_idx),
            jnp.asarray(na.vclass), jnp.asarray(na.valid),
            jnp.asarray(tables["seg"]),
            *(jnp.asarray(tables[k]) for k in SEG_LANE_KEYS),
            u_max=u_max, k_max=u_max,
        )
        if not bool(overflow):
            # v5 ranks are per concat lane == this tree's lane order
            return np.asarray(rank), np.asarray(visible)
    fits = estimate_runs(na.cause_idx, na.vclass, na.valid) <= k_max
    if fits:
        _, rank, visible, _, overflow = merge_weave_kernel_v4_jit(
            jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(na.cause_idx),
            jnp.asarray(na.vclass), jnp.asarray(na.valid), k_max=k_max,
        )
        if not bool(overflow):
            # v4 ranks are per *sorted* lane, but single-tree lanes are
            # already id-sorted, so the identity order carries over
            return np.asarray(rank), np.asarray(visible)
    args = (
        jnp.asarray(hi),
        jnp.asarray(lo),
        jnp.asarray(na.cause_idx),
        jnp.asarray(na.vclass),
        jnp.asarray(na.valid),
    )
    if fits:
        rank, visible, overflow = _linearize_v2_jit(*args, k_max=k_max)
        if not bool(overflow):
            return np.asarray(rank), np.asarray(visible)
    rank, visible = _linearize_jit(*args)
    return np.asarray(rank), np.asarray(visible)


def refresh_list_weave(ct):
    """Full list-weave rebuild on device (the ``weaver="jax"`` path of
    clist.weave). Produces the identical weave list the pure scan
    would. Ids beyond the PackSpec bit layout are off the device
    domain — fall back to the pure rebuild, same stance as nativew's
    OutsideDomain path, so every backend weaves the same trees.

    The marshal goes through the persistent lane cache: a fresh view
    is reused as-is (appends extended it in place), anything else is
    rebuilt once and attached to the result, so the NEXT rebuild or
    merge wave ships cached lanes instead of re-walking the dict."""
    from . import lanecache

    view = lanecache.view_for(ct)
    if view is None:
        from ..collections import clist as c_list

        return c_list.weave(ct.evolve(weaver="pure")).evolve(
            weaver=ct.weaver
        )
    na = view.node_arrays()
    rank, _ = weave_arrays(na, segs=view.segments(na))
    order = np.argsort(rank[: na.capacity], kind="stable")
    weave = [na.nodes[i] for i in order[: na.n]]
    return ct.evolve(weave=weave, lanes=view)


def merge_list_trees(ct1, ct2):
    """Device-backed merge: union the node stores, then one batched
    reweave on device — O((n+m) log) instead of the reference's O(n*m)
    reduce-insert, with an identical resulting tree. Routes through
    the N-way path, which unions cached lane views vectorized (one
    packed-key argsort) when both trees carry them."""
    return merge_many_list_trees((ct1, ct2))


def merge_map_trees(ct1, ct2):
    """Device-backed map merge (map.cljc:248-249 semantics): union the
    node stores host-side, then one device forest linearization over
    the per-key mini-weaves — the map twin of ``merge_list_trees``."""
    from ..collections import shared as s

    return refresh_map_weave(s.union_nodes(ct1, ct2))


def _pure_fleet_fallback(first, cts):
    """N-way union + pure reweave, for fleets off the device domain."""
    from ..collections import clist as c_list
    from ..collections import shared as s

    ct = s.union_nodes_many([first.evolve(weaver="pure")] + cts[1:])
    return c_list.weave(ct).evolve(weaver=first.weaver)


def merge_many_list_trees(cts):
    """Converge a whole fleet of K list replicas into one tree with no
    per-node Python loop: the node-store union is C-speed dict/set
    algebra, every validation the pairwise path performs is done
    vectorized (append-only via dict-items subset tests, cause-must-
    exist via the marshalled cause_idx lanes), and the single reweave
    of the union runs on device. Equals any fold of pairwise merges
    (the weave is a pure function of the node set; reference folds
    pairwise, shared.cljc:300-314)."""
    from ..collections import shared as s

    cts = list(cts)
    if not cts:
        raise s.CausalError("Nothing to merge.", {"causes": {"empty-fleet"}})
    first = cts[0]
    for ct in cts[1:]:
        s.check_mergeable(first, ct)

    # earlier trees win the dict union so a conflict report's
    # existing_node carries the body already in the merge target, not
    # the incoming replica's (bodies only differ in the raising case)
    nodes = {}
    for ct in reversed(cts):
        nodes.update(ct.nodes)
    for ct in cts:
        # C-speed subset test; on failure only, hunt the offender
        if not (ct.nodes.items() <= nodes.items()):
            for nid, body in ct.nodes.items():
                if nodes[nid] != body:
                    raise s.CausalError(
                        "This node is already in the tree and can't be "
                        "changed.",
                        {"causes": {"append-only", "edits-not-allowed"},
                         "existing_node": (nid,) + nodes[nid]},
                    )

    from . import lanecache

    # marshal the union: fold cached views vectorized when every input
    # carries a fresh, rank-compatible one (no dict sort, no per-node
    # Python); otherwise one from-scratch build
    view = None
    in_views = [
        ct.lanes if (isinstance(ct.lanes, lanecache.LaneView)
                     and ct.lanes.n == len(ct.nodes)) else None
        for ct in cts
    ]
    if all(v is not None for v in in_views):
        view = lanecache.union_views_many(in_views)
    if view is None:
        view = lanecache.build_view(nodes, first.uuid)
    na = view.node_arrays() if view is not None \
        else NodeArrays.from_nodes_map(nodes)
    n = na.n
    if na.spec_ok:
        has_cause = na.cause_hi[:n] >= 0
    else:
        # ids overflowed the PackSpec: cause_hi is all -1, so derive
        # "has an id-shaped cause" from the host nodes — the validation
        # must not silently vanish with the device lanes
        from ..ids import is_id

        has_cause = np.fromiter(
            (is_id(cause) for _, cause, _ in na.nodes), bool, n
        )
    dangling = (na.cause_idx[:n] == -1) & has_cause
    if dangling.any():
        # only *incoming* nodes are validated — nodes already in the
        # first tree merge as-is, exactly like the pure N-way union
        # (union_nodes_many checks `added` only) and the pairwise paths,
        # so every backend accepts the same fleets
        first_ids = first.nodes
        for i in np.flatnonzero(dangling):
            if na.nodes[i][0] not in first_ids:
                raise s.CausalError(
                    "The cause of this node is not in the tree.",
                    {"causes": {"cause-must-exist"}, "node": na.nodes[i]},
                )
        # a fleet accepted with pre-existing dangling causes (weft
        # gibberish) is outside the device domain: the kernel parents
        # dangling nodes under root, the pure scan does not. Fall back
        # to the pure reweave of the union — same stance as nativew's
        # OutsideDomain path — so every backend converges identically.
        return _pure_fleet_fallback(first, cts)

    if not na.spec_ok:
        # ids beyond the PackSpec: valid fleet, but no device lanes
        return _pure_fleet_fallback(first, cts)

    rank, _ = weave_arrays(na, segs=view.segments(na) if view else None)
    order = np.argsort(rank[: na.capacity], kind="stable")
    weave = [na.nodes[i] for i in order[:n]]
    # na.nodes is already in sorted id order -> yarns group in one pass
    yarns = {}
    for node in na.nodes:
        yarns.setdefault(node[0][1], []).append(node)
    lamport = max(first.lamport_ts, int(na.ts[:n].max(initial=0)))
    return first.evolve(
        nodes=nodes, yarns=yarns, weave=weave, lamport_ts=lamport,
        lanes=view,
    )


# ------------------------- batched merge kernel -------------------------


def merge_weave_kernel(hi, lo, cause_hi, cause_lo, vclass, valid):
    """Union + reweave for one replica pair, fully on device.

    Inputs are the *concatenated* (hi, lo) id lanes of two trees
    (invalid lanes carry int32 max). Steps: lexsort by id, drop
    duplicate ids (CRDT union — first occurrence wins; divergent bodies
    under one id are reported via the conflict flag), resolve causes by
    a sort-join (queries merged into the key order, forward-filled with
    the last kept node lane via cummax), then linearize.

    Returns ``(order, rank, visible, conflict)`` where ``order`` maps
    sorted lanes back to input lanes, ``rank`` is each sorted lane's
    weave position, ``visible`` the render mask, and ``conflict`` is
    True iff two lanes shared an id with different (cause, vclass)
    bodies (value payloads stay host-side; host equality still governs
    the strict check on the API path).
    """
    order, sorted_lanes = _merge_front_half(hi, lo, cause_hi, cause_lo,
                                            vclass, valid)
    hi_s, lo_s, ci, vclass_s, keep, conflict = sorted_lanes
    rank, visible = linearize(hi_s, lo_s, ci, vclass_s, keep)
    return order, rank, visible, conflict


def merge_weave_kernel_v2(hi, lo, cause_hi, cause_lo, vclass, valid,
                          k_max: int):
    """The merge kernel with the chain-compressed linearizer: identical
    union/cause-resolution front half, v2 back half. Returns
    ``(order, rank, visible, conflict, overflow)``; on overflow the
    rank/visible lanes are invalid and the caller falls back to the
    uncompressed kernel."""
    order, sorted_lanes = _merge_front_half(hi, lo, cause_hi, cause_lo,
                                            vclass, valid)
    hi_s, lo_s, ci, vclass_s, keep, conflict = sorted_lanes
    rank, visible, overflow = linearize_v2(hi_s, lo_s, ci, vclass_s, keep,
                                           k_max)
    return order, rank, visible, conflict, overflow


def _merge_front_half(hi, lo, cause_hi, cause_lo, vclass, valid):
    """Shared union + cause resolution of the merge kernels: id lexsort,
    duplicate drop, conflict detection, sort-join cause resolution."""
    M = hi.shape[0]
    order = jnp.lexsort((lo, hi))
    hi_s, lo_s = hi[order], lo[order]
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool),
         (hi_s[1:] == hi_s[:-1]) & (lo_s[1:] == lo_s[:-1])]
    )
    valid_s = valid[order]
    keep = valid_s & ~dup
    vclass_s = vclass[order]
    chi_s, clo_s = cause_hi[order], cause_lo[order]
    prev_chi = jnp.concatenate([chi_s[:1], chi_s[:-1]])
    prev_clo = jnp.concatenate([clo_s[:1], clo_s[:-1]])
    prev_vc = jnp.concatenate([vclass_s[:1], vclass_s[:-1]])
    conflict = jnp.any(
        dup
        & valid_s
        & ((chi_s != prev_chi) | (clo_s != prev_clo) | (vclass_s != prev_vc))
    )
    rec_hi = jnp.concatenate([jnp.where(keep, hi_s, I32_MAX), chi_s])
    rec_lo = jnp.concatenate([jnp.where(keep, lo_s, I32_MAX), clo_s])
    rec_kind = jnp.concatenate(
        [jnp.zeros(M, jnp.int32), jnp.ones(M, jnp.int32)]
    )
    ord2 = jnp.lexsort((rec_kind, rec_lo, rec_hi))
    is_node_rec = (ord2 < M) & keep[jnp.clip(ord2, 0, M - 1)]
    payload = jnp.where(is_node_rec, ord2.astype(jnp.int32), -1)
    last_node = lax.cummax(payload)
    last_safe = jnp.clip(last_node, 0, M - 1)
    key_hi = jnp.concatenate([hi_s, chi_s])[ord2]
    key_lo = jnp.concatenate([lo_s, clo_s])[ord2]
    match = (
        (last_node >= 0)
        & (hi_s[last_safe] == key_hi)
        & (lo_s[last_safe] == key_lo)
    )
    answer = jnp.where(match, last_node, -1)
    q_lane = jnp.where(is_node_rec, 2 * M, ord2 - M)  # scatter-discard nodes
    ci = (
        jnp.full(2 * M + 1, -1, jnp.int32)
        .at[q_lane]
        .set(answer)[:M]
    )
    return order, (hi_s, lo_s, ci, vclass_s, keep, conflict)


# vmapped batch: [B, M] lanes -> per-replica weave ranks
batched_merge_weave = jax.jit(jax.vmap(merge_weave_kernel))


@partial(jax.jit, static_argnames="k_max")
def batched_merge_weave_v2(hi, lo, cause_hi, cause_lo, vclass, valid,
                           k_max: int):
    """Chain-compressed batch; ``k_max`` is the per-replica run budget.
    When any row overflows it the caller re-runs the uncompressed
    batch (check ``overflow.any()``)."""

    def row(h, l, ch, cl, vc, va):
        return merge_weave_kernel_v2(h, l, ch, cl, vc, va, k_max)

    return jax.vmap(row)(hi, lo, cause_hi, cause_lo, vclass, valid)
