"""Matrix rank-sort — a comparator-free, random-access-free XLA sort.

The round-5 window-1 stage profile put the chip's cost in phase E's
token sorts and rank gathers (~3 s of the 3.75 s headline), and showed
Mosaic Pallas — the VMEM-resident fix — cannot compile on this
tunnel's remote compile helper. This module is the remaining pure-XLA
answer for the sort family: compute each element's *stable-sort
position* (rank) with a BLOCKED O(n^2) comparison count — elementwise
work the VPU streams, with the reduction fused per block so no [n, n]
matrix ever materializes — then invert the permutation with a second
blocked count pass (equality-select of iota: still elementwise, zero
scatters) and apply it via the streaming 128-lane rowgather. No
comparator loop, no per-element HBM transaction anywhere.

Cost model at the kernel's token widths (n ~ 2.3k, batch 1024): two
n^2 elementwise passes ~ 10 G simple int ops (tens of ms across the
batch) + one rowgather per operand (~1.5 ms/site measured class) —
versus XLA's comparator sort whose serialized constants the round-4
arithmetic priced at 300-500 ms per sort at the same shape. The n^2
form inverts at larger n: this is a strategy for the kernels'
few-thousand-lane sort widths, not a general sort.

Semantics: identical to stable ``lax.sort`` (the implicit iota
tie-break makes rank the unique stable order), same contract as
``weaver.bitonic``: int32 operands, last-axis sort, ascending
lexicographic over the first ``num_keys`` operands; remaining operands
ride as payloads. Keys may use the full int32 range including the
``I32_MAX`` invalid-lane sentinel (sentinel lanes sort last among
reals, ahead of padding only by the iota tie-break — exactly as with
``lax.sort`` on the unpadded array).

Reference anchor: one strategy for the batched replacement of the
serial weave linearization at
/root/reference/src/causal/collections/shared.cljc:225-241; the
reference has no vectorized sort to mirror.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .gatherops import rowgather1d

__all__ = ["matrix_sort"]

_I32_MAX = jnp.iinfo(jnp.int32).max
# Query-block width for the n^2 passes: bounds the per-step compare
# intersection to [block, n] even if XLA declines to fuse it, and keeps
# the scan short (n/block steps) so loop overhead stays noise.
_BLOCK = 256


def _lex_lt_block(keys, qkeys):
    """[q, n] lexicographic compare matrix: entry [i, j] is true iff
    element j (full axis) sorts strictly before query element i. The
    final entry of each key list is the iota tie-break, so "before" is
    the stable order and the matrix rows count to unique ranks."""
    lt = None
    eq = None
    for kf, kq in zip(keys, qkeys):
        a = kf[None, :]
        b = kq[:, None]
        this_lt = a < b
        this_eq = a == b
        if lt is None:
            lt, eq = this_lt, this_eq
        else:
            lt = lt | (eq & this_lt)
            eq = eq & this_eq
    return lt


def _matrix_sort_1d(operands, num_keys: int):
    n = operands[0].shape[-1]
    p = -(-n // _BLOCK) * _BLOCK
    iota = jnp.arange(p, dtype=jnp.int32)
    keys = []
    for x in operands[:num_keys]:
        if p != n:
            x = jnp.concatenate(
                [x, jnp.full((p - n,), _I32_MAX, x.dtype)]
            )
        keys.append(x)
    # padding ties with real I32_MAX keys are broken by iota (pads sit
    # past n), so real ranks are exactly 0..n-1 and pads n..p-1
    keys.append(iota)
    starts = jnp.arange(p // _BLOCK, dtype=jnp.int32) * _BLOCK

    def rank_blk(carry, s):
        q = [lax.dynamic_slice_in_dim(k, s, _BLOCK) for k in keys]
        lt = _lex_lt_block(keys, q)
        return carry, jnp.sum(lt.astype(jnp.int32), axis=-1)

    _, ranks = lax.scan(rank_blk, None, starts)
    rank = ranks.reshape(p)

    # invert the permutation with the same blocked idiom (src[r] = the
    # element whose rank is r): equality-select of iota + sum — one
    # term survives per output, so int32 stays exact and nothing
    # scatters
    def src_blk(carry, s):
        r = s + jnp.arange(_BLOCK, dtype=jnp.int32)
        eqm = rank[None, :] == r[:, None]
        return carry, jnp.sum(
            jnp.where(eqm, iota[None, :], 0), axis=-1
        ).astype(jnp.int32)

    _, srcs = lax.scan(src_blk, None, starts)
    src = srcs.reshape(p)[:n]

    outs = []
    for i, x in enumerate(operands):
        # rowgather unconditionally: the strategy's own gather must be
        # the streaming one or a single-switch sort=matrix A/B would
        # re-import the per-element-gather cost it exists to remove
        outs.append(rowgather1d(x, src).astype(x.dtype))
    return tuple(outs)


def matrix_sort(operands, num_keys: int = 1):
    """Stable last-axis lexicographic sort (see module docstring).
    Leading batch dimensions are flattened and vmapped — the blocked
    scans batch transparently."""
    from ..obs import span

    operands = tuple(operands)
    shape = operands[0].shape
    with span("weave.sort.matrix", width=int(shape[-1]),
              n_ops=len(operands)):
        if len(shape) == 1:
            return _matrix_sort_1d(operands, num_keys)
        flat = [x.reshape((-1, shape[-1])) for x in operands]
        out = jax.vmap(lambda *o: _matrix_sort_1d(o, num_keys))(*flat)
        return tuple(x.reshape(shape) for x in out)
