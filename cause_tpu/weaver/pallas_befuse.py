"""The fused v5 token pipeline (phases C-E) as VMEM-resident kernels.

jaxw5's token phases — sort, dedupe, cause redirection, run
extraction, euler ranking, kills, and the lane sort that hands off to
the F expansion — are ~40 XLA ops at token width. Each op is tiny
(~9 KB/row), but XLA lowers the sorts as comparator loops, the
scatters serially, and the cumulative ops as multi-pass reductions;
the one chip datum (PERF.md round 4: TPU slower than CPU at equal
structural work) attributes v5's cost to exactly these serializing
lowerings. This module runs the whole stretch in three Pallas kernels
(composed with the existing ``euler_walk`` and ``pallas_fphase``
kernels by ``jaxw5f``) with one HBM read/write per operand at kernel
edges:

- **K1 sort+redirect** (phase C+D minus the host walk, which jaxw5f
  hoists to XLA pre-sort where the gather strategies apply): the
  9-operand bitonic token sort, the inverse permutation (itself a
  payload-riding sort), duplicate detection, kept-head redirection of
  cause/host links, and the conflict reduction.
- **K2 run extraction** (phase E front): weighted positions,
  adjacency/host-case/contested classification, run numbering, and
  the contracted forest tables. Where jaxw5 gathers seven per-run
  values via searchsorted, K2 *compacts* them with one bitonic
  (token->run compaction = sort by run ordinal at run heads), and
  ``_link_children``'s scatters become inverse-sort rides plus one
  one-hot chunk pass.
- **K4 rank+kills+handoff** (phase E back): run bases expand to
  tokens with the fphase window trick (``run_id`` increments by at
  most 1 per token, so a 128-token tile references at most a 128-run
  window — no scatter, no cumsum), then token kills, the preorder
  -successor sort, and the final lane sort emitting ``(lk, tb_l)``
  for ``pallas_fphase``.

Every kernel processes one replica row at a time inside 8-row grid
blocks; the row computations are PURE functions on [1, P] int32
values (directly unit-testable against the jaxw5 phases with no
Pallas involved — tests/test_befuse.py does exactly that), and the
kernel bodies only loop rows and move refs. The remaining arbitrary
-index gathers are 128-wide one-hot chunks whose lane<->sublane
orientation flips ride one-MXU-dot identity contractions (exact:
every gathered value is a token index, run index, lane index, or
rank, all within +-2^24; the id lanes themselves are sort KEYS and
payloads, never gathered).

Semantics are EXACT vs jaxw5's XLA phases on non-overflow rows; on
overflow rows both pipelines return unspecified values under the same
raised flag. Reference anchor: same as jaxw5 — the weave
linearization of /root/reference/src/causal/collections/shared.cljc
:225-241 at batch width, token-granular.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .arrays import I32_MAX, VCLASS_H_HIDE, VCLASS_HIDE

__all__ = [
    "k1_sort_redirect", "k2_runs", "k4_rank_kills", "next_pow2",
    "row_k1", "row_k2", "row_k4",
]

_LANE = 128
_ROWS = 8
BIG = I32_MAX


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------
# in-kernel building blocks ([1, W] int32 values)
# ---------------------------------------------------------------------

def _eye_f32():
    i0 = lax.broadcasted_iota(jnp.int32, (_LANE, _LANE), 0)
    i1 = lax.broadcasted_iota(jnp.int32, (_LANE, _LANE), 1)
    return (i0 == i1).astype(jnp.float32)


def _flip(eye, v_row):
    """[1, 128] -> [128, 1] via one MXU dot (exact within +-2^24;
    plain reshape in interpret mode — Mosaic has no cheap lane<->
    sublane relayout, XLA:CPU does)."""
    if _interpret():
        return jnp.reshape(v_row, (_LANE, 1))
    return lax.dot_general(
        eye, v_row.astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def _unflip(eye, v_col):
    """[128, 1] -> [1, 128] via one MXU dot (exact within +-2^24;
    plain reshape in interpret mode)."""
    if _interpret():
        return jnp.reshape(v_col, (1, _LANE))
    return lax.dot_general(
        v_col.astype(jnp.float32), eye,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)


def _bitonic_vals(arrs, num_keys):
    """pallas_sort's compare-exchange network on in-kernel values:
    ascending lexicographic over the first ``num_keys`` arrays with an
    implicit original-position tie-break (== stable lax.sort).

    Interpret mode (CPU tests, dryruns) uses stable ``lax.sort``
    itself — the contract twin (tests/test_befuse.py pins the network
    against it directly) — because the unrolled network inside the
    interpreted kernels produces multi-thousand-op XLA:CPU programs
    that exhaust LLVM's memory maps at larger widths. The network path
    is what Mosaic compiles on TPU (and what the jax.export lowering
    guards pin)."""
    if _interpret():
        return list(lax.sort(tuple(arrs), num_keys=num_keys,
                             is_stable=True, dimension=1))
    R, P = arrs[0].shape
    iota = lax.broadcasted_iota(jnp.int32, (R, P), 1)
    arrs = list(arrs) + [iota]
    key_pos = list(range(num_keys)) + [len(arrs) - 1]

    k = 2
    while k <= P:
        j = k // 2
        while j >= 1:
            lower = (iota & j) == 0
            asc = (iota & k) == 0
            partners = [
                jnp.where(lower,
                          jnp.roll(x, -j, axis=1),
                          jnp.roll(x, j, axis=1))
                for x in arrs
            ]
            lt = None
            eq = None
            for kp in key_pos:
                a, b = arrs[kp], partners[kp]
                this_lt = a < b
                this_eq = a == b
                if lt is None:
                    lt, eq = this_lt, this_eq
                else:
                    lt = lt | (eq & this_lt)
                    eq = eq & this_eq
            want_self = lt == (lower == asc)
            arrs = [jnp.where(want_self, x, p_)
                    for x, p_ in zip(arrs, partners)]
            j //= 2
        k *= 2
    return arrs[:-1]


def _cumsum(x):
    """Inclusive prefix sum along lanes via log-shift roll+add
    (int32 wraparound — exact, matching XLA cumsum). Reference op in
    interpret mode (see _bitonic_vals)."""
    if _interpret():
        return jnp.cumsum(x, axis=1, dtype=jnp.int32)
    _, P = x.shape
    col = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    s = 1
    while s < P:
        x = x + jnp.where(col >= s, jnp.roll(x, s, axis=1), 0)
        s *= 2
    return x


def _cummax(x):
    """Inclusive running max along lanes (reference op in interpret
    mode, see _bitonic_vals)."""
    if _interpret():
        return lax.cummax(x, axis=1)
    _, P = x.shape
    col = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    s = 1
    while s < P:
        x = jnp.maximum(
            x, jnp.where(col >= s, jnp.roll(x, s, axis=1),
                         jnp.int32(-BIG - 1)))
        s *= 2
    return x


def _shiftr(x, fill):
    """Previous lane's value (jaxw3._shift1 twin)."""
    col = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(col == 0, fill, jnp.roll(x, 1, axis=1))


def _rolln(x):
    """Next lane's value, wrapping (the concatenate([x[1:], x[:1]])
    idiom of jaxw5)."""
    return jnp.roll(x, -1, axis=1)


def _gather(eye, tables, idx, width=None):
    """``[t[0, i] for i in idx]`` for [1, W] int32 tables sharing one
    [1, Q] index vector: one-hot chunks over the first ``width``
    (default W) table lanes, MXU-contracted. ``idx`` must be
    pre-clipped to [0, width); gathered values must be within +-2^24
    (every caller gathers indices/ranks/lanes, asserted by jaxw5f)."""
    W = tables[0].shape[1]
    width = W if width is None else min(W, width)
    Q = idx.shape[1]
    if _interpret():
        return [jnp.take_along_axis(t, idx, axis=1) for t in tables]
    outs = [jnp.zeros((1, Q), jnp.float32) for _ in tables]
    for c in range(0, width, _LANE):
        i0 = c + lax.broadcasted_iota(jnp.int32, (_LANE, 1), 0)
        mask = (i0 == idx).astype(jnp.float32)        # [128, Q]
        for n, t in enumerate(tables):
            tc = _flip(eye, t[:, c:c + _LANE]).astype(jnp.float32)
            outs[n] = outs[n] + lax.dot_general(
                tc, mask, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return [o.astype(jnp.int32) for o in outs]


def _band(col, t):
    return (col >= t) & (col < t + _LANE)


def _scal_row(col8, *vals):
    """[1, 8] int32 row carrying scalars at positions 0..len-1."""
    out = jnp.zeros((1, 8), jnp.int32)
    for i, v in enumerate(vals):
        out = jnp.where(col8 == i, jnp.broadcast_to(
            jnp.reshape(v, (1, 1)), (1, 8)), out)
    return out


# ---------------------------------------------------------------------
# K1: token sort + dedupe + cause/host redirection (phases C + D)
# ---------------------------------------------------------------------

def row_k1(eye, t_hi, t_lo, t_vc, t_len, t_tsp, t_lane, cu0m, hu0m,
           U: int):
    """One row of phases C+D (pure; [1, P] int32 in/out). Mirrors
    jaxw5.merge_weave_kernel_v5 phases C..D exactly (the host walk is
    pre-resolved by the caller into ``hu0m``)."""
    P = t_hi.shape[1]
    uidx = lax.broadcasted_iota(jnp.int32, (1, P), 1)

    (st_hi, st_lo, t_src, sv_len, sv_vc, sv_tsp, sv_lane,
     sv_cu, sv_hu) = _bitonic_vals(
        (t_hi, t_lo, uidx, t_len, t_vc, t_tsp, t_lane, cu0m, hu0m),
        num_keys=2)
    inv_t = _bitonic_vals((t_src, uidx), num_keys=1)[1]

    tva = ~((st_hi == BIG) & (st_lo == BIG))
    sdup = ((st_hi == _shiftr(st_hi, -1))
            & (st_lo == _shiftr(st_lo, -1))
            & (uidx > 0) & tva)
    keep_t = tva & ~sdup

    thead = _cummax(jnp.where(keep_t, uidx, -1))
    raw_c = _gather(eye, [inv_t], jnp.clip(sv_cu, 0, U - 1))[0]
    red_c = _gather(eye, [thead], jnp.clip(raw_c, 0, U - 1))[0]
    cause_su = jnp.where(sv_cu >= 0, red_c, 0)
    raw_h = _gather(eye, [inv_t], jnp.clip(sv_hu, 0, U - 1))[0]
    red_h = _gather(eye, [thead], jnp.clip(raw_h, 0, U - 1))[0]
    host_su = jnp.where(sv_hu >= 0, red_h, 0)

    special_t = keep_t & (sv_vc > 0)
    parent_su = jnp.where(special_t, cause_su, host_su)

    conflict = jnp.sum(jnp.where(
        sdup & ((sv_vc != _shiftr(sv_vc, 0))
                | (cause_su != _shiftr(cause_su, 0))
                | (sv_len != _shiftr(sv_len, 0))),
        1, 0))

    return (sv_len, sv_vc, sv_tsp, sv_lane, keep_t.astype(jnp.int32),
            cause_su, parent_su, conflict)


# ---------------------------------------------------------------------
# K2: run extraction + contracted forest (phase E front)
# ---------------------------------------------------------------------

def row_k2(eye, sv_len, sv_vc, sv_tsp, keep_i, cause_su, parent_su,
           U: int, k_max: int, Kp: int):
    """One row of phase E's run machinery (pure). Returns the
    contracted-forest tables plus the token-level context K4 needs."""
    P = sv_len.shape[1]
    uidx = lax.broadcasted_iota(jnp.int32, (1, P), 1)
    colP = uidx
    kidx = lax.broadcasted_iota(jnp.int32, (1, Kp), 1)
    targets = kidx + 1
    keep_t = keep_i != 0
    special_t = keep_t & (sv_vc > 0)
    is_root_t = keep_t & (uidx == 0)
    rel_t = keep_t & ~is_root_t

    wcum = _cumsum(jnp.where(keep_t, sv_len, 0))
    wstart = wcum - jnp.where(keep_t, sv_len, 0)
    n_kept = wcum[:, P - 1:P]

    sp_pack = _cummax(jnp.where(
        keep_t, uidx * 2 + (sv_tsp != 0).astype(jnp.int32), -1))
    sp_prev = _shiftr(sp_pack, -1)
    prev_kept = jnp.where(sp_prev >= 0, sp_prev >> 1, -1)
    prev_kept_tsp = (sp_prev >= 0) & (sp_prev % 2 == 1)

    adj = rel_t & (cause_su == prev_kept) & (prev_kept >= 0)
    host_case = adj & ~special_t & prev_kept_tsp
    irregular = rel_t & (~adj | host_case)

    # contested parents: count irregular tokens per parent token
    # (parents are kept tokens, clipped < U by construction)
    psrc = jnp.where(irregular, parent_su, -1)
    contested_i = jnp.zeros((1, P), jnp.int32)
    u_ceil = _LANE * ((U + _LANE - 1) // _LANE)
    for c in range(0, min(P, u_ceil), _LANE):
        i0 = c + lax.broadcasted_iota(jnp.int32, (_LANE, 1), 0)
        cnt = jnp.sum((i0 == psrc).astype(jnp.int32), axis=1,
                      keepdims=True)                  # [128, 1]
        row = _unflip(eye, cnt)                       # [1, 128]
        row = jnp.pad(row, ((0, 0), (c, P - c - _LANE)))
        contested_i = jnp.where(_band(colP, c), row, contested_i)
    contested = contested_i > 0

    ec_pack = _cummax(jnp.where(
        keep_t, uidx * 2 + contested.astype(jnp.int32), -1))
    ec_prev = _shiftr(ec_pack, -1)
    prev_contested = (ec_prev >= 0) & (ec_prev % 2 == 1)
    glued = adj & ~host_case & ~prev_contested

    run_start = keep_t & ~glued
    rs_cum = _cumsum(run_start.astype(jnp.int32))
    run_id = rs_cum - 1
    n_runs = rs_cum[:, P - 1:P]

    # token->run compaction: every per-run head field in ONE sort
    h_parent_tok = jnp.where(irregular, parent_su,
                             jnp.where(adj, prev_kept, -1))
    ckey = jnp.where(run_start, run_id, BIG)
    comp = _bitonic_vals(
        (ckey, uidx, h_parent_tok, wstart,
         special_t.astype(jnp.int32), is_root_t.astype(jnp.int32)),
        num_keys=1)
    hc = comp[1][:, :Kp]
    h_parent_k = comp[2][:, :Kp]
    h_w = comp[3][:, :Kp]
    h_special = comp[4][:, :Kp] != 0
    h_root = comp[5][:, :Kp] != 0

    n_runs_b = jnp.broadcast_to(n_runs, (1, Kp))
    r_valid = targets <= jnp.minimum(n_runs_b, k_max)
    h_parent = jnp.where(r_valid & ~h_root, h_parent_k, -1)
    parent_run = jnp.where(
        h_parent >= 0,
        _gather(eye, [run_id], jnp.clip(h_parent, 0, U - 1))[0],
        -1)

    nxt_w = _rolln(h_w)
    run_w = jnp.where(
        r_valid,
        jnp.where(targets == n_runs_b,
                  jnp.broadcast_to(n_kept, (1, Kp)) - h_w,
                  nxt_w - h_w),
        0)

    parent_sort = jnp.where(r_valid & (parent_run >= 0),
                            parent_run, k_max)
    packed = parent_sort * 2 + (~h_special).astype(jnp.int32)
    _s = _bitonic_vals((packed, -hc, kidx, parent_sort), num_keys=2)
    sord, p_sorted = _s[2], _s[3]
    is_start = (kidx == 0) | (p_sorted != _shiftr(p_sorted, -7))
    same_parent_next = (_rolln(p_sorted) == p_sorted) & (kidx < Kp - 1)
    ns_sorted = jnp.where(same_parent_next, _rolln(sord), -1)
    # scatter-at-permutation == inverse-sort ride
    ns = _bitonic_vals((sord, ns_sorted), num_keys=1)[1]
    # first_child: at most one start per parent value, so the one-hot
    # chunk sum IS the scatter (+1/-1 shifts 0 into the -1 sentinel)
    fc_target = jnp.where(
        is_start & (p_sorted >= 0) & (p_sorted < k_max),
        p_sorted, -1)
    colK = kidx
    fc = jnp.zeros((1, Kp), jnp.int32)
    k_ceil = _LANE * ((k_max + _LANE - 1) // _LANE)
    for c in range(0, min(Kp, k_ceil), _LANE):
        i0 = c + lax.broadcasted_iota(jnp.int32, (_LANE, 1), 0)
        m = i0 == fc_target                           # [128, Kp]
        hit = jnp.sum(m.astype(jnp.int32), axis=1, keepdims=True)
        val = jnp.sum(jnp.where(m, sord, 0), axis=1, keepdims=True)
        row = _unflip(eye, jnp.where(hit > 0, val + 1, 0))
        row = jnp.pad(row, ((0, 0), (c, Kp - c - _LANE)))
        fc = jnp.where(_band(colK, c), row, fc)
    fc = fc - 1

    parent_up = jnp.where(r_valid & (parent_run >= 0), parent_run, -1)
    sp_last = sp_pack[:, P - 1:P]

    return (fc, ns, parent_up, run_w.astype(jnp.int32), hc, h_w,
            run_id, glued.astype(jnp.int32), prev_kept,
            n_runs, n_kept, sp_last)


# ---------------------------------------------------------------------
# K4: run-base expansion + kills + lane-sort handoff (phase E back)
# ---------------------------------------------------------------------

def row_k4(eye, base_run, hc, h_w, run_id, keep_i, sv_len, sv_vc,
           sv_lane, glued_i, prev_kept, cause_su, n_runs, sp_last,
           U: int, k_max: int, N: int, window_expand=None):
    """One row of phase E's ranking/kill tail + the lane-sort handoff
    (pure up to ``window_expand``, which the kernel overrides with a
    pl.ds-windowed form; the default is a plain chunk gather so the
    function is testable standalone)."""
    P = keep_i.shape[1]
    Kp = base_run.shape[1]
    kidx = lax.broadcasted_iota(jnp.int32, (1, Kp), 1)
    targets = kidx + 1
    keep_t = keep_i != 0
    glued = glued_i != 0
    sv_tail_lane = sv_lane + sv_len - 1

    wcum = _cumsum(jnp.where(keep_t, sv_len, 0))
    wstart = wcum - jnp.where(keep_t, sv_len, 0)
    r_valid = targets <= jnp.minimum(n_runs, k_max)

    # run->token expansion: base_ff/ffw == jaxw5's delta-scatter
    # cumsum / run-head cummax fill, telescoped to "my run's value"
    if window_expand is None:
        rid_c = jnp.clip(run_id, 0, Kp - 1)
        base_ff, hw_ff = _gather(eye, [base_run, h_w], rid_c)
    else:
        base_ff, hw_ff = window_expand(base_run, h_w, run_id)

    rank_tok = jnp.where(
        keep_t, base_ff + (wstart - hw_ff), N).astype(jnp.int32)

    hideish = (sv_vc == VCLASS_HIDE) | (sv_vc == VCLASS_H_HIDE)
    kg = glued & hideish
    vict_inrun = jnp.where(
        kg,
        _gather(eye, [sv_tail_lane], jnp.clip(prev_kept, 0, U - 1))[0],
        N)

    bkey = jnp.where(r_valid, base_run, BIG)
    b_sorted, b_src = _bitonic_vals((bkey, kidx), num_keys=1)
    succ_valid = (_rolln(b_sorted) != BIG) & (kidx < Kp - 1)
    succ_entry = jnp.where(succ_valid, _rolln(b_src), -1)
    succ_of = _bitonic_vals((b_src, succ_entry), num_keys=1)[1]
    succ_run = jnp.where(r_valid, succ_of, -1)
    s_c = jnp.clip(
        jnp.where(succ_run >= 0,
                  _gather(eye, [hc],
                          jnp.clip(succ_run, 0, Kp - 1))[0],
                  0),
        0, U - 1)
    g_hide, g_cause = _gather(
        eye, [hideish.astype(jnp.int32), cause_su], s_c)
    s_is_hide = (succ_run >= 0) & (g_hide != 0)
    nxt_head = _rolln(hc)
    tail_tok = jnp.where(
        targets == n_runs,
        jnp.maximum(sp_last >> 1, 0),
        _gather(eye, [prev_kept], jnp.clip(nxt_head, 0, U - 1))[0],
    ).astype(jnp.int32)
    kill_tail = r_valid & s_is_hide & (g_cause == tail_tok)
    vict_tail = jnp.where(
        kill_tail,
        _gather(eye, [sv_tail_lane], jnp.clip(tail_tok, 0, U - 1))[0],
        N)

    lane_key = jnp.where(keep_t & (rank_tok < N), sv_lane, N)
    lk, tb_l = _bitonic_vals((lane_key, rank_tok), num_keys=1)

    # scalar extractions stay int32: Mosaic cannot squeeze bool
    # scalars out of vector registers
    root_val = jnp.where(keep_i[0, 0] != 0, sv_lane[0, 0], N)
    overflow_k = (n_runs[0, 0] > k_max).astype(jnp.int32)

    return (lk, tb_l, vict_inrun.astype(jnp.int32),
            vict_tail.astype(jnp.int32), root_val, overflow_k)


# ---------------------------------------------------------------------
# pallas_call plumbing: 8-row blocks, fori over rows, pl.ds row I/O
# ---------------------------------------------------------------------

def _vmem(width):
    shape = (_ROWS, width)
    imap = lambda b: (b, 0)
    if pltpu is None:  # pragma: no cover - CPU-only jaxlib
        return pl.BlockSpec(shape, imap)
    return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)


def _row(ref, r):
    return ref[pl.ds(r, 1), :]


def _pad_rows(arrs, B):
    Bp = -(-B // _ROWS) * _ROWS
    if Bp == B:
        return arrs, Bp
    return [jnp.pad(x, ((0, Bp - B), (0, 0))) for x in arrs], Bp


@lru_cache(maxsize=None)
def _build_k1(U: int):
    def kernel(*refs):
        ins, outs = refs[:8], refs[8:]
        eye = _eye_f32()
        col8 = lax.broadcasted_iota(jnp.int32, (1, 8), 1)

        def body(r, _):
            res = row_k1(eye, *[_row(x, r) for x in ins], U=U)
            for o, v in zip(outs[:7], res[:7]):
                o[pl.ds(r, 1), :] = v.astype(jnp.int32)
            outs[7][pl.ds(r, 1), :] = _scal_row(col8, res[7])
            return 0

        lax.fori_loop(0, ins[0].shape[0], body, 0)

    def call(*arrs):
        B, P = arrs[0].shape
        arrs, Bp = _pad_rows(list(arrs), B)
        out = pl.pallas_call(
            kernel,
            grid=(Bp // _ROWS,),
            in_specs=[_vmem(P)] * 8,
            out_specs=[_vmem(P)] * 7 + [_vmem(8)],
            out_shape=[jax.ShapeDtypeStruct((Bp, P), jnp.int32)] * 7
            + [jax.ShapeDtypeStruct((Bp, 8), jnp.int32)],
            interpret=_interpret(),
        )(*arrs)
        return tuple(x[:B] for x in out)

    return call


@lru_cache(maxsize=None)
def _build_k2(U: int, k_max: int, Kp: int):
    def kernel(*refs):
        ins, outs = refs[:6], refs[6:]
        eye = _eye_f32()
        col8 = lax.broadcasted_iota(jnp.int32, (1, 8), 1)

        def body(r, _):
            res = row_k2(eye, *[_row(x, r) for x in ins],
                         U=U, k_max=k_max, Kp=Kp)
            for o, v in zip(outs[:9], res[:9]):
                o[pl.ds(r, 1), :] = v.astype(jnp.int32)
            outs[9][pl.ds(r, 1), :] = _scal_row(
                col8, res[9], res[10], res[11])
            return 0

        lax.fori_loop(0, ins[0].shape[0], body, 0)

    def call(*arrs):
        B, P = arrs[0].shape
        arrs, Bp = _pad_rows(list(arrs), B)
        widths = [Kp] * 6 + [P] * 3 + [8]
        out = pl.pallas_call(
            kernel,
            grid=(Bp // _ROWS,),
            in_specs=[_vmem(P)] * 6,
            out_specs=[_vmem(w) for w in widths],
            out_shape=[jax.ShapeDtypeStruct((Bp, w), jnp.int32)
                       for w in widths],
            interpret=_interpret(),
        )(*arrs)
        return tuple(x[:B] for x in out)

    return call


@lru_cache(maxsize=None)
def _build_k4(U: int, k_max: int, N: int):
    def kernel(*refs):
        ins, outs = refs[:12], refs[12:]
        (base_ref, hc_ref, hw_ref, runid_ref, keep_ref, svlen_ref,
         svvc_ref, svlane_ref, glued_ref, prevkept_ref, causesu_ref,
         scal2_ref) = ins
        eye = _eye_f32()
        col8 = lax.broadcasted_iota(jnp.int32, (1, 8), 1)
        P = keep_ref.shape[1]
        Kp = base_ref.shape[1]
        colP = lax.broadcasted_iota(jnp.int32, (1, P), 1)
        i0 = lax.broadcasted_iota(jnp.int32, (_LANE, 1), 0)

        def body(r, _):
            def window_expand(base_run, h_w, run_id):
                base_ff = jnp.zeros((1, P), jnp.int32)
                hw_ff = jnp.zeros((1, P), jnp.int32)
                for t in range(0, P, _LANE):
                    w0 = jnp.clip(runid_ref[r, t], 0, Kp - _LANE)
                    wb = _flip(eye, base_ref[pl.ds(r, 1),
                                             pl.ds(w0, _LANE)])
                    wh = _flip(eye, hw_ref[pl.ds(r, 1),
                                           pl.ds(w0, _LANE)])
                    rid_t = run_id[:, t:t + _LANE]
                    m = (w0 + i0) == rid_t  # [128 window, 128 tok]
                    bsel = jnp.sum(jnp.where(m, wb, 0), axis=0,
                                   keepdims=True)     # [1, 128]
                    hsel = jnp.sum(jnp.where(m, wh, 0), axis=0,
                                   keepdims=True)
                    bsel = jnp.pad(bsel,
                                   ((0, 0), (t, P - t - _LANE)))
                    hsel = jnp.pad(hsel,
                                   ((0, 0), (t, P - t - _LANE)))
                    base_ff = jnp.where(_band(colP, t), bsel,
                                        base_ff)
                    hw_ff = jnp.where(_band(colP, t), hsel, hw_ff)
                return base_ff, hw_ff

            res = row_k4(
                eye,
                _row(base_ref, r), _row(hc_ref, r), _row(hw_ref, r),
                _row(runid_ref, r), _row(keep_ref, r),
                _row(svlen_ref, r), _row(svvc_ref, r),
                _row(svlane_ref, r), _row(glued_ref, r),
                _row(prevkept_ref, r), _row(causesu_ref, r),
                scal2_ref[pl.ds(r, 1), 0:1],
                scal2_ref[pl.ds(r, 1), 2:3],
                U=U, k_max=k_max, N=N,
                window_expand=window_expand)
            for o, v in zip(outs[:4], res[:4]):
                o[pl.ds(r, 1), :] = v
            outs[4][pl.ds(r, 1), :] = _scal_row(col8, res[4], res[5])
            return 0

        lax.fori_loop(0, keep_ref.shape[0], body, 0)

    def call(*arrs):
        B, Kp = arrs[0].shape
        P = arrs[3].shape[1]
        arrs, Bp = _pad_rows(list(arrs), B)
        widths = [P, P, P, Kp, 8]
        out = pl.pallas_call(
            kernel,
            grid=(Bp // _ROWS,),
            in_specs=[_vmem(Kp)] * 3 + [_vmem(P)] * 8 + [_vmem(8)],
            out_specs=[_vmem(w) for w in widths],
            out_shape=[jax.ShapeDtypeStruct((Bp, w), jnp.int32)
                       for w in widths],
            interpret=_interpret(),
        )(*arrs)
        return tuple(x[:B] for x in out)

    return call


@lru_cache(maxsize=None)
def _vmappable(build, *statics):
    """Single-row calling convention over a batch kernel: the row form
    pads to a batch of one; under ``vmap`` the custom-vmap rule swaps
    in the gridded batch kernel (the pallas_sort/pallas_ops pattern,
    which is how the per-row jaxw5f pipeline reaches these)."""
    call = build(*statics)

    @jax.custom_batching.custom_vmap
    def single(*arrs):
        out = call(*[x[None] for x in arrs])
        return tuple(x[0] for x in out)

    @single.def_vmap
    def _vm(axis_size, in_batched, *arrs):
        arrs = tuple(
            x if b else jnp.broadcast_to(x, (axis_size,) + x.shape)
            for x, b in zip(arrs, in_batched))
        out = call(*arrs)
        return out, tuple(True for _ in out)

    return single


def k1_sort_redirect(t_hi, t_lo, t_vc, t_len, t_tsp, t_lane, cu0m,
                     hu0m, U: int):
    """Per-row K1 (batch via vmap). Returns (sv_len, sv_vc, sv_tsp,
    sv_lane, keep_i, cause_su, parent_su, scal); scal[0] =
    conflict."""
    return _vmappable(_build_k1, U)(t_hi, t_lo, t_vc, t_len, t_tsp,
                                    t_lane, cu0m, hu0m)


def k2_runs(sv_len, sv_vc, sv_tsp, keep_i, cause_su, parent_su,
            U: int, k_max: int, Kp: int):
    """Per-row K2 (batch via vmap). Returns (fc, ns, parent_up,
    run_w, hc, h_w, run_id, glued_i, prev_kept, scal); scal =
    [n_runs, n_kept, sp_last, 0...]."""
    return _vmappable(_build_k2, U, k_max, Kp)(
        sv_len, sv_vc, sv_tsp, keep_i, cause_su, parent_su)


def k4_rank_kills(base_run, hc, h_w, run_id, keep_i, sv_len, sv_vc,
                  sv_lane, glued_i, prev_kept, cause_su, scal2,
                  U: int, k_max: int, N: int):
    """Per-row K4 (batch via vmap). Returns (lk, tb_l, vict_inrun,
    vict_tail, scal); scal = [root_val, overflow_k, 0...]."""
    return _vmappable(_build_k4, U, k_max, N)(
        base_run, hc, h_w, run_id, keep_i, sv_len, sv_vc, sv_lane,
        glued_i, prev_kept, cause_su, scal2)
