"""Pallas/Mosaic kernels for the contracted (K-wide) weave phases.

The chain-compressed kernels (jaxw.linearize_v2, jaxw3, jaxw4) shrink
the causal tree to K runs, but still rank the contracted tree with
log-depth pointer doubling (``jaxw._euler_rank``) — 13 rounds of
K-wide gathers that TPU profiling showed dominating the residual cost
(PERF.md): XLA materializes every round as an HBM-width gather pass.

A TPU core walks a K-node tree *sequentially* faster than XLA can
pointer-double it at batch width: the whole run table fits in VMEM
(~9 KB at K~2k), a preorder traversal is ~2 visits per run, and each
visit is a handful of scalar loads — so ``euler_walk`` replaces the
doubling with one Pallas kernel per replica row (the batch dimension
arrives via vmap, which maps onto the Pallas grid). Semantics equal
``_euler_rank``'s weighted preorder base exactly, including the
convention that unreachable/invalid runs rank at ``total`` (they sort
behind every kept lane downstream).

CPU runs (tests, the driver dryrun) execute the same kernel in Pallas
interpret mode — chosen at trace time from the default backend — so
the suite needs no TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["euler_walk"]


def _interpret() -> bool:
    """Interpret off-TPU (tests, dryrun); compile via Mosaic on TPU."""
    return jax.default_backend() != "tpu"


def _specs():
    if pltpu is None:  # pragma: no cover - CPU-only jaxlib
        any_spec = pl.BlockSpec()
        return any_spec, any_spec
    return (pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM))


def _euler_walk_kernel(fc_ref, ns_ref, parent_ref, w_ref, total_ref,
                       base_ref):
    """Preorder walk of one contracted forest.

    state = (cur, pos, mode): mode 0 visits ``cur`` (stamp base, add
    its weight, descend to first child), mode 1 retreats (next sibling
    if any, else climb to parent). One branchless automaton step per
    iteration; terminates when the retreat climbs past the root (the
    root's parent is -1). Runs never reached from run 0 (invalid /
    overflow slots) keep the ``total`` initialization, matching
    ``_euler_rank``.
    """
    K = fc_ref.shape[1]
    base_ref[...] = jnp.full((1, K), total_ref[0, 0], jnp.int32)

    def cond(state):
        cur, _pos, _mode, steps = state
        return (cur >= 0) & (steps < 3 * K + 4)

    def body(state):
        cur, pos, mode, steps = state
        is_visit = mode == 0

        @pl.when(is_visit)
        def _():
            base_ref[0, cur] = pos

        child = fc_ref[0, cur]
        sib = ns_ref[0, cur]
        par = parent_ref[0, cur]
        npos = jnp.where(is_visit, pos + w_ref[0, cur], pos)
        ncur = jnp.where(
            is_visit,
            jnp.where(child >= 0, child, cur),
            jnp.where(sib >= 0, sib, par),
        )
        nmode = jnp.where(
            is_visit,
            jnp.where(child >= 0, 0, 1),
            jnp.where(sib >= 0, 0, 1),
        ).astype(jnp.int32)
        return ncur, npos, nmode, steps + 1

    lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0)),
    )


@functools.partial(jax.jit, static_argnames="k_max")
def euler_walk(fc, ns, parent_run, run_len, k_max: int):
    """Weighted preorder base per run, for one row's contracted tree.

    Inputs are the ``[k_max]`` int32 run tables the compressed kernels
    build (first_child / next_sibling from ``_link_children``, parent
    run ids with -1 at the root/invalid slots, run lengths with 0 at
    invalid slots). Returns ``base`` ``[k_max]`` int32. Under ``vmap``
    the row dimension becomes the Pallas grid.
    """
    vmem, smem = _specs()
    total = jnp.sum(run_len.astype(jnp.int32)).reshape(1, 1)
    out = pl.pallas_call(
        _euler_walk_kernel,
        in_specs=[vmem, vmem, vmem, vmem, smem],
        out_specs=vmem,
        out_shape=jax.ShapeDtypeStruct((1, k_max), jnp.int32),
        interpret=_interpret(),
    )(
        fc.reshape(1, k_max),
        ns.reshape(1, k_max),
        parent_run.reshape(1, k_max),
        run_len.astype(jnp.int32).reshape(1, k_max),
        total,
    )
    return out.reshape(k_max)
