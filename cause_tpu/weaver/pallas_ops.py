"""Pallas/Mosaic kernels for the contracted (K-wide) weave phases.

The chain-compressed kernels (jaxw.linearize_v2, jaxw3, jaxw4, jaxw5)
shrink the causal tree to K runs, but still rank the contracted tree
with log-depth pointer doubling (``jaxw._euler_rank``) — ~12 rounds of
K-wide gathers that TPU profiling showed dominating the residual cost
(PERF.md): XLA materializes every round as an HBM-width gather pass.

A TPU core walks a K-node tree *sequentially* faster than XLA can
pointer-double it at batch width: the run tables sit in VMEM (~9 KB at
K~2k), a preorder traversal is ~2 visits per run, and each visit is a
handful of scalar loads — so ``euler_walk`` replaces the doubling with
one Pallas program per replica row.

Mosaic constraints (discovered via AOT ``jax.export`` for tpu, which
this repo regression-tests — tests/test_pallas_lowering.py — because
the first design only worked in interpret mode):

- scalar STORES to VMEM are unsupported: the per-visit ``base[cur] =
  pos`` scatter goes to an SMEM output; dynamic scalar LOADS from VMEM
  are fine, so the read-only run tables stay in VMEM;
- a batched (squeezed-leading-dim) block fails the (8, 128) tiling
  rule, so batching maps onto an explicit grid of (8 rows, K) blocks
  — ``jax.custom_batching.custom_vmap`` swaps that in when the caller
  vmaps, which is how the v4/v5 kernels reach it.

CPU runs (tests, the driver dryrun) execute the same kernels in Pallas
interpret mode — chosen at trace time from the default backend — so
the suite needs no TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["euler_walk", "euler_walk_batch"]

_ROWS = 8  # rows per grid block (the Mosaic sublane tiling unit)


def _interpret() -> bool:
    """Interpret off-TPU (tests, dryrun); compile via Mosaic on TPU."""
    return jax.default_backend() != "tpu"


def _vmem_spec(R, K):
    if pltpu is None:  # pragma: no cover - CPU-only jaxlib
        return pl.BlockSpec((R, K), lambda b: (b, 0))
    return pl.BlockSpec((R, K), lambda b: (b, 0),
                        memory_space=pltpu.VMEM)


def _smem_spec(R, K):
    if pltpu is None:  # pragma: no cover - CPU-only jaxlib
        return pl.BlockSpec((R, K), lambda b: (b, 0))
    return pl.BlockSpec((R, K), lambda b: (b, 0),
                        memory_space=pltpu.SMEM)


def _walk_kernel(fc_ref, ns_ref, parent_ref, w_ref, base_ref):
    """Preorder walk of each row's contracted forest in the block.

    state = (cur, pos, mode): mode 0 visits ``cur`` (stamp base, add
    its weight, descend to first child), mode 1 retreats (next sibling
    if any, else climb to parent). One branchless automaton step per
    iteration; terminates when the retreat climbs past the root (the
    root's parent is -1). Runs never reached from run 0 (invalid /
    overflow slots) keep the ``total`` initialization, matching
    ``_euler_rank``."""
    R, K = fc_ref.shape

    def row(r, _):
        total = jnp.sum(w_ref[r, :])

        def init(i, __):
            base_ref[r, i] = total
            return 0

        lax.fori_loop(0, K, init, 0)

        def cond(state):
            cur, _pos, _mode, steps = state
            return (cur >= 0) & (steps < 3 * K + 4)

        def body(state):
            cur, pos, mode, steps = state
            is_visit = mode == 0

            @pl.when(is_visit)
            def _():
                base_ref[r, cur] = pos

            child = fc_ref[r, cur]
            sib = ns_ref[r, cur]
            par = parent_ref[r, cur]
            npos = jnp.where(is_visit, pos + w_ref[r, cur], pos)
            ncur = jnp.where(
                is_visit,
                jnp.where(child >= 0, child, cur),
                jnp.where(sib >= 0, sib, par),
            )
            nmode = jnp.where(
                is_visit,
                jnp.where(child >= 0, 0, 1),
                jnp.where(sib >= 0, 0, 1),
            ).astype(jnp.int32)
            return ncur, npos, nmode, steps + 1

        lax.while_loop(
            cond, body,
            (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0)),
        )
        return 0

    lax.fori_loop(0, R, row, 0)


def euler_walk_batch(fc, ns, parent_run, run_len):
    """Weighted preorder base per run for a [B, K] batch of contracted
    forests (grid of _ROWS-row blocks; B pads up to a multiple)."""
    B, K = fc.shape
    Bp = -(-B // _ROWS) * _ROWS
    if Bp != B:
        # padded rows are empty forests (parent -1 everywhere): the
        # automaton visits run 0 and immediately terminates
        pad = ((0, Bp - B), (0, 0))
        fc = jnp.pad(fc, pad, constant_values=-1)
        ns = jnp.pad(ns, pad, constant_values=-1)
        parent_run = jnp.pad(parent_run, pad, constant_values=-1)
        run_len = jnp.pad(run_len, pad, constant_values=0)
    out = pl.pallas_call(
        _walk_kernel,
        grid=(Bp // _ROWS,),
        in_specs=[_vmem_spec(_ROWS, K)] * 4,
        out_specs=_smem_spec(_ROWS, K),
        out_shape=jax.ShapeDtypeStruct((Bp, K), jnp.int32),
        interpret=_interpret(),
    )(fc, ns, parent_run, run_len.astype(jnp.int32))
    return out[:B]


@jax.custom_batching.custom_vmap
def _euler_walk1(fc, ns, parent_run, run_len):
    """Single forest: no grid — whole-array blocks take the untiled
    path, which skips the (8, 128) blocked-shape rule that rejects a
    squeezed/partial block (verified by the AOT export tests)."""
    K = fc.shape[0]
    if pltpu is not None:
        vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
        smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    else:  # pragma: no cover - CPU-only jaxlib
        vmem = smem = pl.BlockSpec()
    out = pl.pallas_call(
        _walk_kernel,
        in_specs=[vmem] * 4,
        out_specs=smem,
        out_shape=jax.ShapeDtypeStruct((1, K), jnp.int32),
        interpret=_interpret(),
    )(
        fc.reshape(1, K), ns.reshape(1, K), parent_run.reshape(1, K),
        run_len.astype(jnp.int32).reshape(1, K),
    )
    return out.reshape(K)


@_euler_walk1.def_vmap
def _euler_walk1_vmap(axis_size, in_batched, fc, ns, parent_run,
                      run_len):
    ops = []
    for x, b in zip((fc, ns, parent_run, run_len), in_batched):
        ops.append(x if b else jnp.broadcast_to(
            x, (axis_size,) + x.shape))
    return euler_walk_batch(*ops), True


def euler_walk(fc, ns, parent_run, run_len, k_max: int):
    """Weighted preorder base per run, for one row's contracted tree.

    Inputs are the ``[k_max]`` int32 run tables the compressed kernels
    build (first_child / next_sibling from ``_link_children``, parent
    run ids with -1 at the root/invalid slots, run lengths with 0 at
    invalid slots). Returns ``base`` ``[k_max]`` int32. Under ``vmap``
    the batch maps onto the Pallas grid via ``euler_walk_batch``."""
    assert fc.shape[-1] == k_max, (fc.shape, k_max)
    return _euler_walk1(fc, ns, parent_run, run_len)
