"""v5f: the v5 merge with its token pipeline fused into Pallas.

Composition (per replica row; batch via ``vmap`` maps every kernel
onto its 8-row grid):

1. jaxw5 phases A+B in XLA (segment ordering, explode/dedupe, token
   construction — S-width tables and the marshal-side gathers where
   the ``CAUSE_TPU_*`` streaming strategies apply), via the
   ``stage="_AB"`` handoff.
2. The phase-D host walk, ALSO in XLA and hoisted BEFORE the token
   sort: the walk only chases cause chains through special nodes, so
   it is sort-independent — hoisting it keeps its data-dependent
   N-table gathers on the XLA side where ``rowgather`` streams them,
   and hands the kernels pure token-index links.
3. ``pallas_befuse.k1_sort_redirect`` — token sort + dedupe +
   kept-head redirection (VMEM bitonic networks).
4. ``pallas_befuse.k2_runs`` — run extraction + contracted forest
   (compaction sorts replace searchsorted gathers and scatters).
5. ``pallas_ops.euler_walk`` — the sequential preorder automaton
   (the v5w euler; bit-exact vs pointer doubling by the v5w parity
   suite).
6. ``pallas_befuse.k4_rank_kills`` — run-base expansion (window
   trick), token kills, and the lane-sort handoff.
7. ``pallas_fphase.fphase_expand`` — the F-phase tile-window
   expansion to concat lanes (rank + visibility).

Between kernels only [*, P]-token-width arrays round-trip HBM (~9 KB
per row per operand — microseconds for the full batch); everything
wider is VMEM-resident inside a kernel. The XLA remainder is phases
A/B, the host walk, and the two kill scatters + coverage tables of
the F glue.

``BENCH_KERNEL=v5f`` selects this path in the benchmarks; exactness
vs ``merge_weave_kernel_v5`` is pinned bit-for-bit on non-overflow
rows by tests/test_befuse.py. Falls back to jaxw5 when the concat
width is incompatible with the F kernel (N % 128 != 0 or N >= 2^24
— the MXU flip exactness bound).

Reference anchor: /root/reference/src/causal/collections/shared.cljc
:225-241 (the weave linearization), at batch width.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .arrays import I32_MAX
from .bitonic import sort_pairs
from .gatherops import take1d
from .jaxw5 import merge_weave_kernel_v5
from .pallas_befuse import (k1_sort_redirect, k2_runs, k4_rank_kills,
                            next_pow2)
from .pallas_fphase import fphase_expand
from .pallas_ops import euler_walk

__all__ = ["merge_weave_kernel_v5f", "batched_merge_weave_v5f"]

BIG = I32_MAX


def merge_weave_kernel_v5f(hi, lo, cci, vclass, valid, seg,
                           sg_min_hi, sg_min_lo, sg_max_hi,
                           sg_max_lo, sg_len, sg_lane0, sg_dense,
                           sg_tail_special, sg_valid, sg_vsum,
                           u_max: int, k_max: int):
    """Fused-token-pipeline v5 for one replica set; contract and
    outputs identical to ``merge_weave_kernel_v5``."""
    N = hi.shape[0]
    if N % 128 != 0 or N >= (1 << 24):
        return merge_weave_kernel_v5(
            hi, lo, cci, vclass, valid, seg, sg_min_hi, sg_min_lo,
            sg_max_hi, sg_max_lo, sg_len, sg_lane0, sg_dense,
            sg_tail_special, sg_valid, sg_vsum,
            u_max=u_max, k_max=k_max)

    ab = merge_weave_kernel_v5(
        hi, lo, cci, vclass, valid, seg, sg_min_hi, sg_min_lo,
        sg_max_hi, sg_max_lo, sg_len, sg_lane0, sg_dense,
        sg_tail_special, sg_valid, sg_vsum,
        u_max=u_max, k_max=k_max, stage="_AB")

    U = u_max
    P = next_pow2(max(U, 128))
    Kp = next_pow2(max(k_max, 128))

    # ---- phase-D prep in XLA (presort; rides the gather switches) --
    tva0 = ~((ab.t_hi == BIG) & (ab.t_lo == BIG))
    cl0 = jnp.where(
        tva0, take1d(cci, jnp.clip(ab.t_lane, 0, N - 1)), -1)
    cu0m = jnp.where(cl0 >= 0, ab.token_of_lane(cl0), -1)

    # host walk: chase cause chains through specials (sort-independent
    # — each token's walk depends only on the lane tables, so it runs
    # presort; jaxw5 runs the identical recurrence post-sort)
    chase = tva0 & (ab.t_vc == 0)

    def wcond(c):
        p, i = c
        pc = jnp.clip(p, 0, N - 1)
        on = chase & (p >= 0) & (take1d(vclass, pc) > 0)
        return (i < N) & jnp.any(on)

    def wbody(c):
        p, i = c
        pc = jnp.clip(p, 0, N - 1)
        on = chase & (p >= 0) & (take1d(vclass, pc) > 0)
        return jnp.where(on, take1d(cci, pc), p), i + 1

    host_lane, _ = lax.while_loop(wcond, wbody, (cl0, jnp.int32(0)))
    hu0m = jnp.where(host_lane >= 0, ab.token_of_lane(host_lane), -1)

    def pad_p(x, fill):
        if P == U:
            return x.astype(jnp.int32)
        return jnp.concatenate(
            [x.astype(jnp.int32), jnp.full((P - U,), fill, jnp.int32)]
        )

    # ---- the fused token pipeline ---------------------------------
    (sv_len, sv_vc, sv_tsp, sv_lane, keep_i, cause_su, parent_su,
     scal1) = k1_sort_redirect(
        pad_p(ab.t_hi, BIG), pad_p(ab.t_lo, BIG), pad_p(ab.t_vc, 0),
        pad_p(ab.t_len, 0), pad_p(ab.t_tsp, 0), pad_p(ab.t_lane, 0),
        pad_p(cu0m, -1), pad_p(hu0m, -1), U=U)
    conflict = scal1[0] != 0

    (fc, ns, parent_up, run_w, hc, h_w, run_id, glued_i, prev_kept,
     scal2) = k2_runs(sv_len, sv_vc, sv_tsp, keep_i, cause_su,
                      parent_su, U=U, k_max=k_max, Kp=Kp)

    base_run = euler_walk(fc, ns, parent_up, run_w, Kp)

    lk, tb_l, vict_in, vict_tail, scal4 = k4_rank_kills(
        base_run, hc, h_w, run_id, keep_i, sv_len, sv_vc, sv_lane,
        glued_i, prev_kept, cause_su, scal2,
        U=U, k_max=k_max, N=N)
    root_val = scal4[0]
    overflow_k = scal4[1] != 0

    # ---- F glue (jaxw5's fused-F branch, verbatim semantics) -------
    killed_sc = jnp.zeros(N + 1, bool)
    killed_sc = killed_sc.at[vict_in].set(True, mode="drop")
    killed_sc = killed_sc.at[vict_tail].set(True, mode="drop")
    root_lane = jnp.zeros(N, bool).at[
        jnp.clip(root_val, 0, N - 1)
    ].set(root_val < N)
    killed_ext = killed_sc[:N] | root_lane

    seg_cov = sg_valid & take1d(ab.survive, ab.inv_s)
    cov_start = jnp.where(seg_cov, sg_lane0, N).astype(jnp.int32)
    cov_end = jnp.where(seg_cov, sg_lane0 + sg_len, 0).astype(
        jnp.int32)
    cs, ce = sort_pairs((cov_start, cov_end), num_keys=1)
    flags = (valid.astype(jnp.int32)
             | (killed_ext.astype(jnp.int32) << 1))
    rank_lane, visible = fphase_expand(
        lk, tb_l, cs, ce, vclass, seg, flags)

    overflow = ab.overflow_u | overflow_k
    return rank_lane, visible, conflict, overflow


merge_weave_kernel_v5f_jit = jax.jit(
    merge_weave_kernel_v5f, static_argnames=("u_max", "k_max"))


@partial(jax.jit, static_argnames=("u_max", "k_max"))
def batched_merge_weave_v5f(hi, lo, cci, vclass, valid, seg,
                            sg_min_hi, sg_min_lo, sg_max_hi,
                            sg_max_lo, sg_len, sg_lane0, sg_dense,
                            sg_tail_special, sg_valid, sg_vsum,
                            u_max: int, k_max: int):
    """Batched v5f: [B, N] lanes + [B, S] segment tables ->
    per-replica (rank, visible, conflict, overflow), like
    ``batched_merge_weave_v5``."""

    def row(*a):
        return merge_weave_kernel_v5f(*a, u_max=u_max, k_max=k_max)

    return jax.vmap(row)(hi, lo, cci, vclass, valid, seg,
                         sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                         sg_len, sg_lane0, sg_dense, sg_tail_special,
                         sg_valid, sg_vsum)
