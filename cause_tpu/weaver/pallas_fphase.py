"""The F-phase tile-window fusion: v5's lane expansion in one kernel.

Phase F of the v5 segment-union kernel (jaxw5) expands token-width
results back to the full concat-lane width: per-lane rank fills,
segment coverage, and visibility. The XLA form is delta scatters into
[N]-buffers, three N-width cumsums, and ~10 elementwise N-passes —
every one an HBM round trip, and the scatters serialize. The round-3
chip stage attribution predicts this phase dominates v5 once the
token phases stream (PERF.md "Round 4": the tile-window theorem).

This module computes the same values inside ONE Pallas kernel per
8-row block with zero scatters and zero cumsums, from two facts:

- kept tokens have DISTINCT lanes (each surviving segment contributes
  its head lane once; exploded segments contribute each lane once;
  duplicate-id tokens are dropped before ranking), so a 128-lane tile
  intersects at most 128 tokens. The per-lane fill (the last kept
  token at or before the lane — exactly what the XLA delta-cumsum
  telescopes to) is therefore computable per tile from a FIXED
  128-token window starting at ``searchsorted(token_lanes,
  tile_start)``, via a [window=128, lanes=128] compare-select matrix
  in VMEM, with the single token before the window as the carry.
- covered segments are disjoint contiguous lane runs, so per-lane
  coverage (``in_surviving``) is the same rightmost-start-at-or-
  before query against the sorted coverage table, testing the
  selected segment's end.

Mosaic layout note: every ref keeps the natural [rows, width]
orientation (whole-width blocks satisfy the (8, 128) tiling rule; a
transposed (width, 8) block does not). A window loads as a [1, 128]
lane-oriented slice and is flipped to the [128, 1] sublane orientation
with one tiny MXU dot against the identity (int values here are
< 2^24, so the f32 contraction is exact) — Mosaic has no cheap
relayout, but a 128x128x1 matmul is effectively free. The
compare-select matrices are then [window=128 sublanes, lane=128
lanes] and reduce along sublanes into [1, 128] results that store
straight into the [B, N] outputs. Window-start tables are
precomputed in XLA as tiny [B, T] comparison-matrix searchsorteds
(T = N/128 tiles).

Visibility (the pure elementwise tail: next-lane tombstone checks,
kill flags, value-class masks) runs as a second vectorized pass over
the whole [8, N] block inside the same kernel. The only F-phase work
left in XLA are the U-width kill scatters (duplicate victims are
possible, so they are genuine scatters) and the root-lane bit, both
folded into one input bit-plane.

Replaces the weave linearization of
/root/reference/src/causal/collections/shared.cljc:225-241 at batch
width (same anchor as jaxw5 phase F). ``CAUSE_TPU_FPHASE=pallas``
flips jaxw5 at trace time; bit-exactness vs the XLA form is pinned by
tests/test_fphase.py and the Mosaic lowering by
tests/test_pallas_lowering.py.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only module; absent on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .arrays import VCLASS_H_HIDE, VCLASS_HIDE

__all__ = ["fphase_expand"]

_ROWS = 8  # rows per grid block (the Mosaic sublane tiling unit)
_LANE = 128


def _interpret() -> bool:
    """Interpret off-TPU (tests, dryrun); compile via Mosaic on TPU."""
    return jax.default_backend() != "tpu"


def _kernel(lk_ref, tb_ref, cs_ref, ce_ref, tw0_ref, sw0_ref,
            vc_ref, seg_ref, fl_ref, rank_ref, vis_ref):
    """One 8-row block: per-(row, tile) window fills, then the
    vectorized visibility pass over the whole block."""
    R, N = vc_ref.shape
    Up = lk_ref.shape[1]
    Sp = cs_ref.shape[1]
    T = N // _LANE
    i0 = lax.broadcasted_iota(jnp.int32, (_LANE, _LANE), 0)  # window j
    i1 = lax.broadcasted_iota(jnp.int32, (1, _LANE), 1)      # lane pos
    eye = (i0 == lax.broadcasted_iota(
        jnp.int32, (_LANE, _LANE), 1)).astype(jnp.float32)

    def flip(v_row):
        """[1, 128] -> [128, 1] via one MXU dot (exact: |v| < 2^24)."""
        return lax.dot_general(
            eye, v_row.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)

    def row(r, _):
        def tile(t, __):
            lane = t * _LANE + i1                         # [1, 128]

            # ---- token fill window --------------------------------
            c0t = tw0_ref[r, t]
            ws = jnp.clip(c0t, 0, Up - _LANE)
            wlk = flip(lk_ref[pl.ds(r, 1), pl.ds(ws, _LANE)])
            wtb = flip(tb_ref[pl.ds(r, 1), pl.ds(ws, _LANE)])
            m = wlk <= lane                               # [128, 128]
            jmax = jnp.max(jnp.where(m, i0, -1), axis=0,
                           keepdims=True)                 # [1, 128]
            found = jmax >= 0
            sel = i0 == jmax
            bsel = jnp.sum(jnp.where(sel, wtb, 0), axis=0,
                           keepdims=True)
            lsel = jnp.sum(jnp.where(sel, wlk, 0), axis=0,
                           keepdims=True)
            ci = jnp.maximum(c0t - 1, 0)
            has_c = c0t > 0
            cb = jnp.where(has_c, tb_ref[r, ci], 0)
            cl = jnp.where(has_c, lk_ref[r, ci], 0)
            base_f = jnp.where(found, bsel, cb)
            lane_f = jnp.where(found, lsel, cl)
            has_tok = found & (lsel == lane)

            # ---- segment coverage window --------------------------
            c0s = sw0_ref[r, t]
            ss = jnp.clip(c0s, 0, Sp - _LANE)
            wcs = flip(cs_ref[pl.ds(r, 1), pl.ds(ss, _LANE)])
            wce = flip(ce_ref[pl.ds(r, 1), pl.ds(ss, _LANE)])
            m2 = wcs <= lane
            j2 = jnp.max(jnp.where(m2, i0, -1), axis=0,
                         keepdims=True)
            f2 = j2 >= 0
            esel = jnp.sum(jnp.where(i0 == j2, wce, 0), axis=0,
                           keepdims=True)
            si = jnp.maximum(c0s - 1, 0)
            ce_carry = jnp.where(c0s > 0, ce_ref[r, si], 0)
            in_surv = jnp.where(f2, esel, ce_carry) > lane

            fl = fl_ref[pl.ds(r, 1), pl.ds(t * _LANE, _LANE)]
            valid = (fl & 1) > 0
            rank_t = jnp.where(
                valid & (in_surv | has_tok),
                base_f + (lane - lane_f), N
            ).astype(jnp.int32)
            rank_ref[pl.ds(r, 1), pl.ds(t * _LANE, _LANE)] = rank_t
            # stash coverage for the visibility pass
            vis_ref[pl.ds(r, 1), pl.ds(t * _LANE, _LANE)] = (
                in_surv.astype(jnp.int32))
            return 0

        lax.fori_loop(0, T, tile, 0)
        return 0

    lax.fori_loop(0, R, row, 0)

    # ---- visibility: one vectorized pass over the block -----------
    rank = rank_ref[:, :]
    in_surv = vis_ref[:, :] > 0
    vc = vc_ref[:, :]
    seg = seg_ref[:, :]
    fl = fl_ref[:, :]
    valid = (fl & 1) > 0
    killed_ext = (fl & 2) > 0  # kill scatters + root lane (from XLA)
    col = lax.broadcasted_iota(jnp.int32, (R, N), 1)
    not_last = col < N - 1
    hide = ((vc == VCLASS_HIDE) | (vc == VCLASS_H_HIDE)).astype(
        jnp.int32)
    nxt_same = (jnp.roll(seg, -1, axis=1) == seg) & (seg >= 0) \
        & not_last
    nxt_hide = (jnp.roll(hide, -1, axis=1) > 0) & not_last
    kill_in = in_surv & nxt_same & nxt_hide
    vis_ref[:, :] = (
        valid & (rank < N) & (vc == 0) & ~killed_ext & ~kill_in
    ).astype(jnp.int32)


@lru_cache(maxsize=None)
def _build():
    def kernel(*refs):
        _kernel(*refs)

    def batch_call(lk, tb, cs, ce, tw0, sw0, vc, seg, fl):
        B, N = vc.shape
        Up = lk.shape[1]
        Sp = cs.shape[1]
        T = tw0.shape[1]
        Bp = -(-B // _ROWS) * _ROWS
        if Bp != B:
            # padded rows: flags 0 => valid False => rank N, vis 0
            pad = ((0, Bp - B), (0, 0))
            lk, tb, cs, ce, tw0, sw0, vc, seg, fl = (
                jnp.pad(x, pad) for x in
                (lk, tb, cs, ce, tw0, sw0, vc, seg, fl))
        def vmem(width):
            # blocks cover the whole width (satisfies the tiling rule
            # for widths that are not 128-multiples, e.g. T) and walk
            # the replica axis in 8-row steps
            shape = (_ROWS, width)
            imap = lambda b: (b, 0)
            if pltpu is None:  # pragma: no cover - CPU-only jaxlib
                return pl.BlockSpec(shape, imap)
            return pl.BlockSpec(shape, imap,
                                memory_space=pltpu.VMEM)

        out = pl.pallas_call(
            kernel,
            grid=(Bp // _ROWS,),
            in_specs=[
                vmem(Up), vmem(Up), vmem(Sp), vmem(Sp),
                vmem(T), vmem(T),
                vmem(N), vmem(N), vmem(N),
            ],
            out_specs=[vmem(N)] * 2,
            out_shape=[jax.ShapeDtypeStruct((Bp, N), jnp.int32)] * 2,
            interpret=_interpret(),
        )(lk, tb, cs, ce, tw0, sw0, vc, seg, fl)
        return tuple(x[:B] for x in out)

    @jax.custom_batching.custom_vmap
    def single(lk, tb, cs, ce, tw0, sw0, vc, seg, fl):
        out = batch_call(*(x[None] for x in
                           (lk, tb, cs, ce, tw0, sw0, vc, seg, fl)))
        return tuple(x[0] for x in out)

    @single.def_vmap
    def _single_vmap(axis_size, in_batched, *ops):
        ops = tuple(
            x if b else jnp.broadcast_to(x, (axis_size,) + x.shape)
            for x, b in zip(ops, in_batched))
        return batch_call(*ops), (True, True)

    return single, batch_call


def fphase_expand(lk, tb_l, cov_start, cov_end, vclass, seg, flags):
    """Per-lane (rank, visible) for one row, fused in VMEM.

    ``lk``/``tb_l``: lane-sorted kept-token lanes (N sentinel past the
    kept prefix) and their token bases, as phase F's lane sort emits.
    ``cov_start``/``cov_end``: the SORTED surviving-segment coverage
    table (start ascending; sentinel entries start=N, end=0).
    ``vclass``/``seg``: the kernel's per-lane value-class and segment
    ordinals. ``flags``: bit 0 = lane valid, bit 1 = killed-external
    (the U-width kill scatters + the root lane, still XLA-side).

    Requires ``N % 128 == 0`` (the jaxw5 caller falls back to the XLA
    form otherwise). Under ``vmap`` the batch maps onto the Pallas
    grid of 8-row blocks.
    """
    N = vclass.shape[-1]
    assert N % _LANE == 0, N
    # the in-kernel window flips contract through f32 (exact only
    # below 2^24); every windowed value is a lane index or rank < N
    assert N < (1 << 24), N
    T = N // _LANE

    # pad token/coverage tables to >= one window
    fill_lk = jnp.full((1,), N, jnp.int32)
    Up = max(_LANE, lk.shape[-1])
    if lk.shape[-1] < Up:
        pad_n = Up - lk.shape[-1]
        lk = jnp.concatenate(
            [lk, jnp.broadcast_to(fill_lk, (pad_n,))])
        tb_l = jnp.concatenate(
            [tb_l, jnp.zeros((pad_n,), jnp.int32)])
    Sp = max(_LANE, cov_start.shape[-1])
    if cov_start.shape[-1] < Sp:
        pad_n = Sp - cov_start.shape[-1]
        cov_start = jnp.concatenate(
            [cov_start, jnp.full((pad_n,), N, jnp.int32)])
        cov_end = jnp.concatenate(
            [cov_end, jnp.zeros((pad_n,), jnp.int32)])

    # [T] window starts: comparison-matrix searchsorted (tiny)
    starts = (jnp.arange(T, dtype=jnp.int32) * _LANE)
    tw0 = jnp.sum(
        (lk[None, :] < starts[:, None]), axis=1).astype(jnp.int32)
    sw0 = jnp.sum(
        (cov_start[None, :] < starts[:, None]), axis=1
    ).astype(jnp.int32)

    single, _ = _build()
    rank, vis = single(lk, tb_l, cov_start, cov_end, tw0, sw0,
                       vclass, seg, flags)
    return rank, vis > 0
