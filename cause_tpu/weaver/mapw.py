"""Batched/sharded device path for MAP trees: key-rooted forests on
the list merge kernels.

Map weaves are first-class in the reference (map.cljc:21-45, merge at
:248-249): every key holds a mini list-weave — key-caused nodes hang
at the key's root in recency order, id-caused nodes hang under their
target (undo by id). That IS a forest of list-weave components, so the
batched device story reuses the list machinery wholesale: encode each
map tree as lanes over a synthetic id space —

- lane 0: one global root, id ``(-2, 0)`` (sorts below everything;
  the kernels' "sorted lane 0 is the root" contract);
- next: one key-root lane per key present in the tree, id
  ``(-1, key_rank)`` — key ranks interned over the UNION of keys in a
  batch (same contract as ``SiteInterner`` for sites), so two
  replicas' roots for one key carry the SAME id and the kernel's
  duplicate elimination dedupes them exactly like shared base nodes;
- then the real nodes in ascending id order: key-caused lanes point
  ``cci`` at their key root, id-caused lanes at their target.

Since real ids are non-negative, synthetic ids can never collide, and
within each tree the lane order remains ascending-id (the v4 kernel's
per-tree contract, jaxw4.merge_weave_kernel_v4). The merged per-key
weave falls out of the kernel's Euler order: each key subtree is
contiguous, specials-first / descending-id sibling order is exactly
map recency order, and id-caused chains resolve through the same
host-jump the list path uses. ``batched_merge_map_weave`` vmaps the
v4 kernel over replica pairs; the sharded variant rides
``parallel.mesh.sharded_merge_weave_v4`` unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..ids import is_id
from .arrays import (
    DEFAULT_PACK,
    I32_MAX,
    OutsideDomain,
    SiteInterner,
    next_pow2,
    vclass_of,
)

__all__ = [
    "key_table",
    "forest_lanes",
    "pair_rows",
    "batched_merge_map_weave",
    "batched_merge_map_weave_v5",
    "map_v5_inputs",
    "sharded_merge_map_weave",
    "sharded_merge_map_weave_v5",
    "merged_map_weave",
    "map_row_digest",
    "MapWaveResult",
    "merge_map_wave",
]

GLOBAL_ROOT_HI = np.int32(-2)
KEY_ROOT_HI = np.int32(-1)


def _key_sort_token(k) -> tuple:
    """Deterministic, type-stable ordering token for map keys (keys may
    mix keywords, strings, numbers — Python can't compare those
    directly)."""
    return (type(k).__name__, repr(k))


def key_table(trees_nodes: Sequence[dict]) -> Dict[object, int]:
    """Rank every key appearing across the batch (order-preserving
    over the union — the key twin of SiteInterner's contract)."""
    keys = set()
    for nodes_map in trees_nodes:
        for cause, _v in nodes_map.values():
            if not is_id(cause) and cause is not None:
                keys.add(cause)
    ordered = sorted(keys, key=_key_sort_token)
    return {k: i for i, k in enumerate(ordered)}


def forest_lanes(nodes_map: dict, key_rank: Dict[object, int],
                 interner: SiteInterner, cap: int,
                 spec=DEFAULT_PACK):
    """One map tree as forest lanes padded to ``cap``.

    Returns ``(hi, lo, cci, vc, valid, lane_nodes, lane_keys)`` where
    ``lane_nodes[i]`` is the host node triple of a real lane (None for
    synthetic lanes) and ``lane_keys`` the key of each key-root lane.
    Raises OutsideDomain for shapes the pure weaver defines but the
    forest encoding doesn't (dangling id causes, id-caused targets that
    are themselves id-caused — same domain rule as ``map_lanes``).
    """
    ids = sorted(nodes_map)
    present = set()
    for cause, _v in nodes_map.values():
        if not is_id(cause):
            present.add(cause)
    tree_keys = sorted(present, key=_key_sort_token)
    n_keys = len(tree_keys)
    n = 1 + n_keys + len(ids)
    if n > cap:
        raise OverflowError(f"capacity {cap} < {n} forest lanes")
    if ids:
        # ids beyond the PackSpec bit layout would silently wrap the
        # packed lo lane and reorder the merge — same off-device stance
        # as NodeArrays.from_nodes_map
        try:
            spec.check(max(i[0] for i in ids), len(interner),
                       max(i[2] for i in ids))
        except OverflowError:
            raise OutsideDomain() from None

    hi = np.full(cap, I32_MAX, np.int32)
    lo = np.full(cap, I32_MAX, np.int32)
    cci = np.full(cap, -1, np.int32)
    vc = np.zeros(cap, np.int32)
    valid = np.zeros(cap, bool)
    lane_nodes: List[Optional[tuple]] = [None] * cap
    lane_keys: List[Optional[object]] = [None] * cap

    hi[0], lo[0] = GLOBAL_ROOT_HI, 0
    valid[0] = True
    key_lane = {}
    for j, k in enumerate(tree_keys):
        lane = 1 + j
        hi[lane] = KEY_ROOT_HI
        lo[lane] = key_rank[k]
        cci[lane] = 0
        valid[lane] = True
        lane_keys[lane] = k
        key_lane[k] = lane

    idx_of = {nid: 1 + n_keys + i for i, nid in enumerate(ids)}
    rank = interner.rank
    n_real = len(ids)
    if n_real:
        base = 1 + n_keys
        sl = slice(base, base + n_real)
        # vectorized columns (dict lookups stay Python — they carry the
        # domain checks — but the numeric packing is numpy)
        hi[sl] = np.fromiter((nid[0] for nid in ids), np.int64, n_real)
        site_r = np.fromiter((rank[nid[1]] for nid in ids), np.int64,
                             n_real)
        tx_r = np.fromiter((nid[2] for nid in ids), np.int64, n_real)
        lo[sl] = spec.pack_lo(site_r.astype(np.int32),
                              tx_r.astype(np.int32))
        valid[sl] = True
        bodies = [nodes_map[nid] for nid in ids]
        vc[sl] = np.fromiter((vclass_of(v) for _, v in bodies), np.int32,
                             n_real)

        def resolve(cause):
            if is_id(cause):
                t = idx_of.get(tuple(cause))
                if t is None:
                    raise OutsideDomain()  # dangling target
                if is_id(nodes_map[tuple(cause)][0]):
                    raise OutsideDomain()  # id-caused targeting id-caused
                return t
            return key_lane[cause]

        cci[sl] = np.fromiter((resolve(c) for c, _ in bodies), np.int64,
                              n_real)
        for i, nid in enumerate(ids):
            lane_nodes[base + i] = (nid, bodies[i][0], bodies[i][1])
    return hi, lo, cci, vc, valid, lane_nodes, lane_keys


def pair_rows(pairs: Sequence[Tuple[dict, dict]],
              spec=DEFAULT_PACK):
    """[B, 2*cap] forest-lane batch for replica pairs of one map doc.

    Key ranks and site ranks are interned over the whole batch, so
    every row's synthetic and real ids are mutually comparable and
    shared keys/nodes dedupe on device. Returns ``(lanes, meta)``:
    ``lanes`` the dict of [B, 2*cap] arrays (v4 LANE_KEYS4 layout),
    ``meta`` the per-row host artifacts for ``merged_map_weave``.
    """
    from ..obs import span as _span

    with _span("mapw.pair_rows", pairs=len(pairs)):
        trees = [t for pair in pairs for t in pair]
        krank = key_table(trees)
        interner = SiteInterner(
            nid[1] for t in trees for nid in t
        )
        cap = next_pow2(max(
            1 + len(krank) + len(t) for t in trees
        ))
        B = len(pairs)
        N = 2 * cap
        out = {
            "hi": np.full((B, N), I32_MAX, np.int32),
            "lo": np.full((B, N), I32_MAX, np.int32),
            "cci": np.full((B, N), -1, np.int32),
            "vc": np.zeros((B, N), np.int32),
            "valid": np.zeros((B, N), bool),
        }
        meta = []
        for r, (ta, tb) in enumerate(pairs):
            row_meta = []
            for t, nodes_map in enumerate((ta, tb)):
                off = t * cap
                (hi, lo, cci, vc, valid, lane_nodes,
                 lane_keys) = forest_lanes(
                    nodes_map, krank, interner, cap, spec
                )
                sl = slice(off, off + cap)
                out["hi"][r, sl] = hi
                out["lo"][r, sl] = lo
                out["cci"][r, sl] = np.where(cci >= 0, cci + off, -1)
                out["vc"][r, sl] = vc
                out["valid"][r, sl] = valid
                row_meta.append((lane_nodes, lane_keys))
            meta.append(row_meta)
        return out, {"rows": meta, "capacity": cap, "key_rank": krank}


def batched_merge_map_weave(lanes: Dict[str, np.ndarray], k_max: int = 0):
    """Run the batched map-forest merge on device: vmapped v4 kernel
    over [B, 2*cap] forest lanes. ``k_max`` 0 sizes the run budget at
    full width (map forests have no chain runs to compress — every
    key-caused node is a sibling, so the run count ~ lane count).
    Returns ``(order, rank, visible, conflict, overflow)`` per row."""
    from .jaxw4 import batched_merge_weave_v4

    if k_max <= 0:
        k_max = int(lanes["hi"].shape[1])
    return batched_merge_weave_v4(
        *(jnp.asarray(lanes[k]) for k in ("hi", "lo", "cci", "vc", "valid")),
        k_max=k_max,
    )


def map_v5_inputs(lanes: Dict[str, np.ndarray], cap: int):
    """Segment-union (v5) inputs for forest-lane rows: the SAME
    marshal the list path uses (benchgen.batched_v5_inputs — segment
    extraction is id-layout-agnostic; synthetic key-root ids sort
    below every real id, so per-tree lanes stay ascending and the
    shared key roots dedupe as single-lane twins exactly like shared
    base segments). Returns ``(v5lanes, u_budget)``."""
    from .. import benchgen

    v5b = benchgen.batched_v5_inputs(lanes, cap)
    return v5b, benchgen.v5_token_budget(v5b)


def batched_merge_map_weave_v5(lanes: Dict[str, np.ndarray], cap: int,
                               u_max: int = 0, v5b=None):
    """The v5 segment-union route for map forests (round-5: map
    fleets no longer pay full node width for the union — merge cost
    scales with divergence, like list fleets). Returns ``(rank,
    visible, conflict, overflow)`` in CONCAT-LANE coordinates (the v5
    contract: no order array) plus the effective token budget.
    ``v5b``: pre-marshalled segment lanes (``map_v5_inputs``) so an
    overflow retry does not redo the host-side segment extraction."""
    from .. import benchgen
    from .jaxw5 import batched_merge_weave_v5

    if v5b is None:
        v5b, est = map_v5_inputs(lanes, cap)
        if u_max <= 0:
            u_max = est
    elif u_max <= 0:
        from .. import benchgen as _b

        u_max = _b.v5_token_budget(v5b)
    out = batched_merge_weave_v5(
        *(jnp.asarray(v5b[k]) for k in benchgen.LANE_KEYS5),
        u_max=u_max, k_max=u_max,
    )
    return out, u_max


def sharded_merge_map_weave_v5(mesh, lanes: Dict[str, np.ndarray],
                               cap: int, u_max: int = 0):
    """Sharded twin of the v5 map route: forest v5 lanes ride
    ``parallel.mesh.sharded_merge_weave_v5`` unchanged (replica axis
    over the mesh, digests psum'd fleet-wide)."""
    from ..parallel.mesh import sharded_merge_weave_v5

    v5b, est = map_v5_inputs(lanes, cap)
    if u_max <= 0:
        u_max = est
    return sharded_merge_weave_v5(mesh, v5b, u_max, u_max), u_max


def sharded_merge_map_weave(mesh, lanes: Dict[str, np.ndarray],
                            k_max: int = 0):
    """The sharded twin: map forests ride the v4 sharded step
    unchanged (parallel.mesh.sharded_merge_weave_v4) — replica axis
    over the mesh, digests psum'd fleet-wide."""
    from ..parallel.mesh import sharded_merge_weave_v4

    if k_max <= 0:
        k_max = int(lanes["hi"].shape[1])
    return sharded_merge_weave_v4(
        mesh, jnp.asarray(lanes["hi"]), jnp.asarray(lanes["lo"]),
        jnp.asarray(lanes["cci"]), jnp.asarray(lanes["vc"]),
        jnp.asarray(lanes["valid"]), k_max,
    )


def merged_map_weave(lanes, meta, order, rank, row: int):
    """Rebuild pair ``row``'s merged per-key weave dict from the
    kernel's order — the map twin of the list paths' rank argsort.
    Key subtrees are contiguous in Euler order; each key's segment
    starts at its key-root lane.

    ``order`` is the v4 sorted-lane permutation; ``None`` means the
    v5 contract — ``rank`` is already indexed by concat lane."""
    from ..ids import ROOT_ID, ROOT_NODE

    cap = meta["capacity"]
    rank_r = np.asarray(rank[row])
    N = 2 * cap
    # presort-lane visit order: sorted positions ordered by rank
    kept = rank_r < N
    pos = np.flatnonzero(kept)
    pos = pos[np.argsort(rank_r[pos], kind="stable")]
    if order is None:
        lanes_in_order = pos
    else:
        lanes_in_order = np.asarray(order[row])[pos]
    (nodes_a, keys_a), (nodes_b, keys_b) = meta["rows"][row]

    weave: Dict[object, list] = {}
    current = None
    for lane in lanes_in_order:
        lane = int(lane)
        t, j = divmod(lane, cap)
        lane_nodes, lane_keys = (nodes_a, keys_a) if t == 0 else (
            nodes_b, keys_b)
        if lane_keys[j] is not None:
            current = lane_keys[j]
            weave.setdefault(current, [ROOT_NODE])
            continue
        nd = lane_nodes[j]
        if nd is None:
            continue  # the global root
        nid, cause, value = nd
        in_weave_cause = cause if is_id(cause) else ROOT_ID
        weave[current].append((nid, in_weave_cause, value))
    return weave


def map_row_digest(lanes, order, rank, visible):
    """Per-row uint32 digests over the forest lanes — bit-identical to
    the sharded path's device digest (parallel.mesh._fleet_stats):
    the v4 kernel reports rank/visible per SORTED lane, so the id
    lanes are re-sorted by ``order`` before the avalanche mix (pinned
    by tests/test_mapw.py against the sharded output). ``order=None``
    is the v5 contract — rank/visible already index concat lanes, and
    the mix is lane-order-invariant."""
    if order is None:
        hi = np.asarray(lanes["hi"]).astype(np.uint32)
        lo = np.asarray(lanes["lo"]).astype(np.uint32)
    else:
        order = np.asarray(order).astype(np.int64)
        hi = np.take_along_axis(
            lanes["hi"], order, axis=1).astype(np.uint32)
        lo = np.take_along_axis(
            lanes["lo"], order, axis=1).astype(np.uint32)
    rank = np.asarray(rank).astype(np.int64)
    m = rank.shape[1]
    keptm = rank < m
    pos = np.where(keptm, rank, 0).astype(np.uint32)
    vis = np.asarray(visible).astype(np.uint32)
    x = (
        hi * np.uint32(0x9E3779B1)
        + lo * np.uint32(0x85EBCA77)
        + pos * np.uint32(0xC2B2AE35)
        + vis * np.uint32(40503)
        + np.uint32(1)
    )
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> np.uint32(16))
    return np.where(keptm, x, np.uint32(0)).sum(axis=1, dtype=np.uint32)


class MapWaveResult:
    """Converged device state of a map-fleet wave + lazy host
    materialization (the map twin of parallel.wave.WaveResult)."""

    def __init__(self, pairs, lanes, meta, order, rank, visible, digest,
                 fallback=None, digest_valid=None):
        self._pairs = pairs
        self._lanes = lanes
        self._meta = meta
        self._order = order
        self._rank = rank
        self._visible = visible
        self.digest = digest
        self._fallback = fallback or {}
        self.digest_valid = (
            digest_valid if digest_valid is not None
            else np.ones(len(pairs), bool)
        )

    @property
    def fallback(self):
        return sorted(self._fallback)

    def __len__(self):
        return len(self._pairs)

    def merged(self, i: int):
        """Pair ``i``'s converged CausalMap handle — identical to
        ``pairs[i][0].merge(pairs[i][1])`` (with the same append-only
        body validation)."""
        from ..collections import shared as s

        if i in self._fallback:
            return self._fallback[i]
        a, b = self._pairs[i]
        nodes = dict(a.ct.nodes)
        s.check_no_conflicting_bodies(nodes, b.ct.nodes)
        nodes.update(b.ct.nodes)
        weave = merged_map_weave(self._lanes, self._meta, self._order,
                                 self._rank, i)
        lamport = max(
            a.ct.lamport_ts, b.ct.lamport_ts,
            max((nid[0] for nid in nodes), default=0),
        )
        ct = s.spin(a.ct.evolve(nodes=nodes, weave=weave,
                                lamport_ts=lamport))
        return type(a)(ct)


def merge_map_wave(pairs, kernel: str = "v5") -> MapWaveResult:
    """Converge many CausalMap replica pairs in one batched device
    dispatch — the map twin of ``parallel.merge_wave`` (map trees
    cannot ride the list-lane wave; their forest encoding lives here).
    Pairs outside the forest domain (exotic id-cause chains, weft
    gibberish, PackSpec overflow) fall back to the per-pair host merge
    exactly like the list wave's fallback. Body validation between
    duplicate ids is host-side in ``merged``, same contract.

    ``kernel``: "v5" (default since round 5 — the segment-union
    route: the shared parts of a map fleet union at segment
    granularity, so the union cost scales with divergence instead of
    full node width, matching the list fleets) or "v4" (the original
    full-width forest route)."""
    from ..collections import shared as s
    from ..obs import span as _span

    pairs = list(pairs)
    if not pairs:
        raise s.CausalError("Nothing to merge.",
                            {"causes": {"empty-fleet"}})
    with _span("mapw.merge_wave", pairs=len(pairs), kernel=kernel):
        return _merge_map_wave(pairs, kernel)


def _merge_map_wave(pairs, kernel: str) -> MapWaveResult:
    from ..collections import shared as s

    for a, b in pairs:
        s.check_mergeable(a.ct, b.ct)
        if a.ct.type != "map":
            raise s.CausalError(
                "merge_map_wave is for map trees; use "
                "parallel.merge_wave for list-shaped fleets",
                {"causes": {"type-missmatch"}, "type": a.ct.type},
            )

    # batch-level key/site tables cover every tree (fallback pairs
    # included: extra entries cost rank space, not correctness)
    trees = [t.ct.nodes for pair in pairs for t in pair]
    krank = key_table(trees)
    interner = SiteInterner(nid[1] for t in trees for nid in t)
    cap = next_pow2(max(1 + len(krank) + len(t) for t in trees))
    fallback = {}
    live = []
    live_rows = []
    for i, (a, b) in enumerate(pairs):
        try:
            row = [forest_lanes(a.ct.nodes, krank, interner, cap),
                   forest_lanes(b.ct.nodes, krank, interner, cap)]
        except OutsideDomain:
            fallback[i] = a.merge(b)
            continue
        live.append(i)
        live_rows.append(row)

    B = len(pairs)
    dig_valid = np.zeros(B, bool)
    digest = np.zeros(B, np.uint32)
    if not live:
        return MapWaveResult(pairs, None, {"rows": [], "capacity": cap},
                             None, None, None, digest, fallback,
                             dig_valid)
    N = 2 * cap
    lanes = {
        "hi": np.full((len(live), N), I32_MAX, np.int32),
        "lo": np.full((len(live), N), I32_MAX, np.int32),
        "cci": np.full((len(live), N), -1, np.int32),
        "vc": np.zeros((len(live), N), np.int32),
        "valid": np.zeros((len(live), N), bool),
    }
    meta_rows = []
    for r, row in enumerate(live_rows):
        rm = []
        for t, (hi, lo, cci, vc, valid, lane_nodes, lane_keys) in enumerate(
                row):
            sl = slice(t * cap, (t + 1) * cap)
            lanes["hi"][r, sl] = hi
            lanes["lo"][r, sl] = lo
            lanes["cci"][r, sl] = np.where(cci >= 0, cci + t * cap, -1)
            lanes["vc"][r, sl] = vc
            lanes["valid"][r, sl] = valid
            rm.append((lane_nodes, lane_keys))
        meta_rows.append(rm)
    meta = {"rows": meta_rows, "capacity": cap, "key_rank": krank}

    if kernel == "v4":
        order, rank, visible, _conflict, overflow = (
            batched_merge_map_weave(lanes))
        if bool(np.asarray(overflow).any()):  # pragma: no cover
            raise s.CausalError("map wave overflowed its run budget",
                                {"causes": {"token-overflow"}})
        order = np.asarray(order)
        row_ovf = np.zeros(len(live), bool)
    elif kernel == "v5":
        # segment-union route; the overflow flag backstops the sampled
        # token estimate — double and re-dispatch (the segment marshal
        # is done once, only the device program re-runs), and rows
        # that STILL overflow fall back to the host merge per row
        v5b, u = map_v5_inputs(lanes, cap)
        for _ in range(3):
            (rank, visible, _conflict, overflow), u = (
                batched_merge_map_weave_v5(lanes, cap, u_max=u,
                                           v5b=v5b))
            row_ovf = np.asarray(overflow).astype(bool)
            if not row_ovf.any():
                break
            u *= 2
        order = None
    else:
        raise ValueError(
            f"merge_map_wave kernel must be 'v5' or 'v4', got "
            f"{kernel!r}")
    rank = np.asarray(rank)
    visible = np.asarray(visible)
    live_digest = map_row_digest(lanes, order, rank, visible)

    # expand live rows back to the full index space; overflowed v5
    # rows carry garbage ranks — they join the host-merge fallback
    full_order = None if order is None else np.zeros((B, N), np.int32)
    full_rank = np.full((B, N), N, np.int32)
    full_vis = np.zeros((B, N), bool)
    full_meta = [None] * B
    for j, i in enumerate(live):
        if row_ovf[j]:
            a, b = pairs[i]
            fallback[i] = a.merge(b)
            continue
        if order is not None:
            full_order[i] = order[j]
        full_rank[i] = rank[j]
        full_vis[i] = visible[j]
        full_meta[i] = meta_rows[j]
        digest[i] = live_digest[j]
        dig_valid[i] = True
    # merged_map_weave indexes meta["rows"][i] and the full arrays
    full_lanes = {
        k: np.zeros((B,) + v.shape[1:], v.dtype) for k, v in lanes.items()
    }
    for j, i in enumerate(live):
        for k in full_lanes:
            full_lanes[k][i] = lanes[k][j]
    meta_full = {"rows": full_meta, "capacity": cap, "key_rank": krank}
    return MapWaveResult(pairs, full_lanes, meta_full, full_order,
                         full_rank, full_vis, digest, fallback, dig_valid)
