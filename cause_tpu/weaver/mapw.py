"""Batched/sharded device path for MAP trees: key-rooted forests on
the list merge kernels.

Map weaves are first-class in the reference (map.cljc:21-45, merge at
:248-249): every key holds a mini list-weave — key-caused nodes hang
at the key's root in recency order, id-caused nodes hang under their
target (undo by id). That IS a forest of list-weave components, so the
batched device story reuses the list machinery wholesale: encode each
map tree as lanes over a synthetic id space —

- lane 0: one global root, id ``(-2, 0)`` (sorts below everything;
  the kernels' "sorted lane 0 is the root" contract);
- next: one key-root lane per key present in the tree, id
  ``(-1, key_rank)`` — key ranks interned over the UNION of keys in a
  batch (same contract as ``SiteInterner`` for sites), so two
  replicas' roots for one key carry the SAME id and the kernel's
  duplicate elimination dedupes them exactly like shared base nodes;
- then the real nodes in ascending id order: key-caused lanes point
  ``cci`` at their key root, id-caused lanes at their target.

Since real ids are non-negative, synthetic ids can never collide, and
within each tree the lane order remains ascending-id (the v4 kernel's
per-tree contract, jaxw4.merge_weave_kernel_v4). The merged per-key
weave falls out of the kernel's Euler order: each key subtree is
contiguous, specials-first / descending-id sibling order is exactly
map recency order, and id-caused chains resolve through the same
host-jump the list path uses. ``batched_merge_map_weave`` vmaps the
v4 kernel over replica pairs; the sharded variant rides
``parallel.mesh.sharded_merge_weave_v4`` unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..ids import is_id
from .arrays import (
    DEFAULT_PACK,
    I32_MAX,
    OutsideDomain,
    SiteInterner,
    next_pow2,
    vclass_of,
)

__all__ = [
    "key_table",
    "forest_lanes",
    "pair_rows",
    "batched_merge_map_weave",
    "sharded_merge_map_weave",
    "merged_map_weave",
    "map_row_digest",
]

GLOBAL_ROOT_HI = np.int32(-2)
KEY_ROOT_HI = np.int32(-1)


def _key_sort_token(k) -> tuple:
    """Deterministic, type-stable ordering token for map keys (keys may
    mix keywords, strings, numbers — Python can't compare those
    directly)."""
    return (type(k).__name__, repr(k))


def key_table(trees_nodes: Sequence[dict]) -> Dict[object, int]:
    """Rank every key appearing across the batch (order-preserving
    over the union — the key twin of SiteInterner's contract)."""
    keys = set()
    for nodes_map in trees_nodes:
        for cause, _v in nodes_map.values():
            if not is_id(cause) and cause is not None:
                keys.add(cause)
    ordered = sorted(keys, key=_key_sort_token)
    return {k: i for i, k in enumerate(ordered)}


def forest_lanes(nodes_map: dict, key_rank: Dict[object, int],
                 interner: SiteInterner, cap: int,
                 spec=DEFAULT_PACK):
    """One map tree as forest lanes padded to ``cap``.

    Returns ``(hi, lo, cci, vc, valid, lane_nodes, lane_keys)`` where
    ``lane_nodes[i]`` is the host node triple of a real lane (None for
    synthetic lanes) and ``lane_keys`` the key of each key-root lane.
    Raises OutsideDomain for shapes the pure weaver defines but the
    forest encoding doesn't (dangling id causes, id-caused targets that
    are themselves id-caused — same domain rule as ``map_lanes``).
    """
    ids = sorted(nodes_map)
    present = set()
    for cause, _v in nodes_map.values():
        if not is_id(cause):
            present.add(cause)
    tree_keys = sorted(present, key=_key_sort_token)
    n_keys = len(tree_keys)
    n = 1 + n_keys + len(ids)
    if n > cap:
        raise OverflowError(f"capacity {cap} < {n} forest lanes")

    hi = np.full(cap, I32_MAX, np.int32)
    lo = np.full(cap, I32_MAX, np.int32)
    cci = np.full(cap, -1, np.int32)
    vc = np.zeros(cap, np.int32)
    valid = np.zeros(cap, bool)
    lane_nodes: List[Optional[tuple]] = [None] * cap
    lane_keys: List[Optional[object]] = [None] * cap

    hi[0], lo[0] = GLOBAL_ROOT_HI, 0
    valid[0] = True
    key_lane = {}
    for j, k in enumerate(tree_keys):
        lane = 1 + j
        hi[lane] = KEY_ROOT_HI
        lo[lane] = key_rank[k]
        cci[lane] = 0
        valid[lane] = True
        lane_keys[lane] = k
        key_lane[k] = lane

    idx_of = {nid: 1 + n_keys + i for i, nid in enumerate(ids)}
    rank = interner.rank
    n_real = len(ids)
    if n_real:
        base = 1 + n_keys
        sl = slice(base, base + n_real)
        # vectorized columns (dict lookups stay Python — they carry the
        # domain checks — but the numeric packing is numpy)
        hi[sl] = np.fromiter((nid[0] for nid in ids), np.int64, n_real)
        site_r = np.fromiter((rank[nid[1]] for nid in ids), np.int64,
                             n_real)
        tx_r = np.fromiter((nid[2] for nid in ids), np.int64, n_real)
        lo[sl] = spec.pack_lo(site_r.astype(np.int32),
                              tx_r.astype(np.int32))
        valid[sl] = True
        bodies = [nodes_map[nid] for nid in ids]
        vc[sl] = np.fromiter((vclass_of(v) for _, v in bodies), np.int32,
                             n_real)

        def resolve(cause):
            if is_id(cause):
                t = idx_of.get(tuple(cause))
                if t is None:
                    raise OutsideDomain()  # dangling target
                if is_id(nodes_map[tuple(cause)][0]):
                    raise OutsideDomain()  # id-caused targeting id-caused
                return t
            return key_lane[cause]

        cci[sl] = np.fromiter((resolve(c) for c, _ in bodies), np.int64,
                              n_real)
        for i, nid in enumerate(ids):
            lane_nodes[base + i] = (nid, bodies[i][0], bodies[i][1])
    return hi, lo, cci, vc, valid, lane_nodes, lane_keys


def pair_rows(pairs: Sequence[Tuple[dict, dict]],
              spec=DEFAULT_PACK):
    """[B, 2*cap] forest-lane batch for replica pairs of one map doc.

    Key ranks and site ranks are interned over the whole batch, so
    every row's synthetic and real ids are mutually comparable and
    shared keys/nodes dedupe on device. Returns ``(lanes, meta)``:
    ``lanes`` the dict of [B, 2*cap] arrays (v4 LANE_KEYS4 layout),
    ``meta`` the per-row host artifacts for ``merged_map_weave``.
    """
    trees = [t for pair in pairs for t in pair]
    krank = key_table(trees)
    interner = SiteInterner(
        nid[1] for t in trees for nid in t
    )
    cap = next_pow2(max(
        1 + len(krank) + len(t) for t in trees
    ))
    B = len(pairs)
    N = 2 * cap
    out = {
        "hi": np.full((B, N), I32_MAX, np.int32),
        "lo": np.full((B, N), I32_MAX, np.int32),
        "cci": np.full((B, N), -1, np.int32),
        "vc": np.zeros((B, N), np.int32),
        "valid": np.zeros((B, N), bool),
    }
    meta = []
    for r, (ta, tb) in enumerate(pairs):
        row_meta = []
        for t, nodes_map in enumerate((ta, tb)):
            off = t * cap
            hi, lo, cci, vc, valid, lane_nodes, lane_keys = forest_lanes(
                nodes_map, krank, interner, cap, spec
            )
            sl = slice(off, off + cap)
            out["hi"][r, sl] = hi
            out["lo"][r, sl] = lo
            out["cci"][r, sl] = np.where(cci >= 0, cci + off, -1)
            out["vc"][r, sl] = vc
            out["valid"][r, sl] = valid
            row_meta.append((lane_nodes, lane_keys))
        meta.append(row_meta)
    return out, {"rows": meta, "capacity": cap, "key_rank": krank}


def batched_merge_map_weave(lanes: Dict[str, np.ndarray], k_max: int = 0):
    """Run the batched map-forest merge on device: vmapped v4 kernel
    over [B, 2*cap] forest lanes. ``k_max`` 0 sizes the run budget at
    full width (map forests have no chain runs to compress — every
    key-caused node is a sibling, so the run count ~ lane count).
    Returns ``(order, rank, visible, conflict, overflow)`` per row."""
    from .jaxw4 import batched_merge_weave_v4

    if k_max <= 0:
        k_max = int(lanes["hi"].shape[1])
    return batched_merge_weave_v4(
        *(jnp.asarray(lanes[k]) for k in ("hi", "lo", "cci", "vc", "valid")),
        k_max=k_max,
    )


def sharded_merge_map_weave(mesh, lanes: Dict[str, np.ndarray],
                            k_max: int = 0):
    """The sharded twin: map forests ride the v4 sharded step
    unchanged (parallel.mesh.sharded_merge_weave_v4) — replica axis
    over the mesh, digests psum'd fleet-wide."""
    from ..parallel.mesh import sharded_merge_weave_v4

    if k_max <= 0:
        k_max = int(lanes["hi"].shape[1])
    return sharded_merge_weave_v4(
        mesh, jnp.asarray(lanes["hi"]), jnp.asarray(lanes["lo"]),
        jnp.asarray(lanes["cci"]), jnp.asarray(lanes["vc"]),
        jnp.asarray(lanes["valid"]), k_max,
    )


def merged_map_weave(lanes, meta, order, rank, row: int):
    """Rebuild pair ``row``'s merged per-key weave dict from the
    kernel's order — the map twin of the list paths' rank argsort.
    Key subtrees are contiguous in Euler order; each key's segment
    starts at its key-root lane."""
    from ..ids import ROOT_ID, ROOT_NODE

    cap = meta["capacity"]
    order_r = np.asarray(order[row])
    rank_r = np.asarray(rank[row])
    N = 2 * cap
    # presort-lane visit order: sorted positions ordered by rank
    kept = rank_r < N
    pos = np.flatnonzero(kept)
    pos = pos[np.argsort(rank_r[pos], kind="stable")]
    lanes_in_order = order_r[pos]
    (nodes_a, keys_a), (nodes_b, keys_b) = meta["rows"][row]

    weave: Dict[object, list] = {}
    current = None
    for lane in lanes_in_order:
        lane = int(lane)
        t, j = divmod(lane, cap)
        lane_nodes, lane_keys = (nodes_a, keys_a) if t == 0 else (
            nodes_b, keys_b)
        if lane_keys[j] is not None:
            current = lane_keys[j]
            weave.setdefault(current, [ROOT_NODE])
            continue
        nd = lane_nodes[j]
        if nd is None:
            continue  # the global root
        nid, cause, value = nd
        in_weave_cause = cause if is_id(cause) else ROOT_ID
        weave[current].append((nid, in_weave_cause, value))
    return weave


def map_row_digest(lanes, rank, visible):
    """Per-row uint32 digests over the forest lanes (same mix as
    parallel.mesh.replica_digest, computed host-side on the raw lanes
    — rank coordinates must match ``rank``'s)."""
    hi = lanes["hi"].astype(np.uint32)
    lo = lanes["lo"].astype(np.uint32)
    rank = np.asarray(rank).astype(np.int64)
    m = rank.shape[1]
    keptm = rank < m
    pos = np.where(keptm, rank, 0).astype(np.uint32)
    vis = np.asarray(visible).astype(np.uint32)
    mix = (
        hi * np.uint32(0x9E3779B1)
        ^ lo * np.uint32(0x85EBCA77)
        ^ (pos * np.uint32(2654435761) + vis * np.uint32(40503)
           + np.uint32(1))
    )
    return np.where(keptm, mix, np.uint32(0)).sum(axis=1, dtype=np.uint32)
