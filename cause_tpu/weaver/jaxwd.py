"""Delta-native weave programs: steady-state wave cost ∝ divergence.

Every wave generation so far — v5 included — dispatches at full
document width: the token pipeline scales with divergence, but the
lane phases (F expansion, digest) and the host assembly pay O(doc) per
wave even when 1024 replicas diverge by a handful of ops. PERF.md's
phase profile and the PR-6 ``wave.cost`` stream both show it: cost is
O(doc). This module is the other half of the segment-union design —
when the converged weave is *resident* (FleetSession keeps lanes, and
the last wave's ranks/visibility, on device), a steady-state wave only
needs to reweave the **divergent window** and splice the result back:

- the *window* is a tiny self-contained replica pair: one **anchor**
  lane (the final node of the converged resident weave, playing the
  root) plus each tree's divergent-suffix lanes. Within the delta
  domain (every divergent lane's cause resolves inside the window or
  to the anchor; no tombstone targets the anchor; see
  ``parallel.wave.delta_domain_ok``) the full weave factors exactly::

      weave(union) = weave(converged prefix) ++ weave(window) \\ anchor

  because every divergent node descends from the anchor and the anchor
  is the last element of the prefix weave — so prefix ranks and
  visibility are FROZEN and the window's v5 ranks, offset by the
  anchor's rank ``r0``, ARE the full-weave ranks of the divergent
  lanes. This is a semantic identity of the causal-tree linearization
  (sibling order depends only on ids/specialness, both local to the
  window), not a kernel coincidence; tests/test_delta_weave.py pins it
  against ``merge`` and the full kernel bit-for-bit.
- the *digest* is incremental and EXACT: ``mesh.replica_digest`` is a
  permutation-invariant uint32 wraparound sum of per-lane avalanche
  terms, so ``digest(full) = digest(prefix terms) + digest(window
  terms)`` with window positions offset by ``r0``. The prefix sum is
  computed once per rebuild and rides along as a [B] uint32 input.
- the *splice* is a buffer-donated masked scatter updating the
  resident full-width rank/visibility arrays in place, so on-demand
  host materialization (``WaveResult.merged``) keeps working after
  delta waves.

Budgets: the window kernel runs with ``u_max = k_max = N_w`` (the
window width), which makes token/run overflow structurally impossible
— a window can never mint more tokens than it has lanes. The only
overflow left is the window *capacity* itself (divergence outgrowing
the session's pow2 window budget), which falls back to a full-width
rebuild — the "first contact or budget overflow" policy of ROADMAP
item 1.

Consumers (PR 8): beyond the steady-state ``FleetSession`` wave, the
merge reduction tree (``parallel.tree``) batches each of its
ceil(log2(n)) fleet-convergence levels as ONE ``batched_delta_weave``
dispatch — per pair the two "trees" are pooled subtree sides under
the shared anchor, and the returned digest is each merged subtree's
TOTAL document digest, so per-level convergence evidence costs no
extra dispatch. ``batched_weave_digest`` is the tree's full-width
level (first contact / window-budget bounce) and the sweep/harvest
control arm.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .jaxw5 import merge_weave_kernel_v5

__all__ = [
    "batched_delta_weave",
    "batched_weave_digest",
    "splice_ranks",
]


@partial(jax.jit, static_argnames=("u_max", "k_max"))
def batched_weave_digest(hi, lo, cci, vclass, valid, seg,
                         sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                         sg_len, sg_lane0, sg_dense, sg_tail_special,
                         sg_valid, sg_vsum, u_max: int, k_max: int):
    """The full-width control program: one fused dispatch running the
    batched v5 segment-union kernel AND the per-row convergence digest.
    Returns ``(rank, visible, digest, overflow)``. This is what the
    divergence sweep and the harvest digest gate time as the
    full-weave A/B arm — kernel + digest in one program, the same
    shape of work a session's full wave performs in two."""
    from ..parallel.mesh import replica_digest

    def row(*a):
        return merge_weave_kernel_v5(*a, u_max=u_max, k_max=k_max)

    rank, visible, conflict, overflow = jax.vmap(row)(
        hi, lo, cci, vclass, valid, seg,
        sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
        sg_len, sg_lane0, sg_dense, sg_tail_special, sg_valid, sg_vsum)
    digest = jax.vmap(replica_digest)(hi, lo, rank, visible)
    return rank, visible, digest, overflow


@partial(jax.jit, static_argnames=("u_max", "k_max"))
def batched_delta_weave(hi, lo, cci, vclass, valid, seg,
                        sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
                        sg_len, sg_lane0, sg_dense, sg_tail_special,
                        sg_valid, sg_vsum, prefix_digest, r0,
                        u_max: int, k_max: int):
    """The delta wave: v5 segment-union over the divergent WINDOW plus
    the incremental digest, in one dispatch.

    Window lanes are ``[B, 2*wcap]`` — per tree, lane 0 is the anchor
    (the converged weave's final node, presented as the window root)
    followed by that tree's divergent-suffix lanes. ``prefix_digest``
    is the [B] uint32 sum of the resident prefix's avalanche terms
    (frozen ranks/visibility, anchor included); ``r0`` is the [B]
    anchor rank (``shared_prefix_len - 1``).

    Returns ``(rank_w, visible_w, digest, overflow)``: window-local
    ranks (full rank = ``r0 + rank_w``; the splice applies the
    offset), window visibility, the TOTAL document digest — bit
    -identical to what the full-width wave would compute — and the
    per-row overflow flag (structurally False when callers follow the
    ``u_max = k_max = N_w`` budget rule; kept as a safety net).
    """
    from ..parallel.mesh import mix32

    def row(*a):
        return merge_weave_kernel_v5(*a, u_max=u_max, k_max=k_max)

    rank_w, visible_w, _conflict, overflow = jax.vmap(row)(
        hi, lo, cci, vclass, valid, seg,
        sg_min_hi, sg_min_lo, sg_max_hi, sg_max_lo,
        sg_len, sg_lane0, sg_dense, sg_tail_special, sg_valid, sg_vsum)

    B, Nw = hi.shape
    wcap = Nw // 2
    lane = jnp.arange(Nw, dtype=jnp.int32)
    # the anchor lanes (one copy per tree) belong to the PREFIX digest:
    # the kept copy ranks 0 in the window but carries the prefix's own
    # rank/visibility in the full weave; the twin-dropped copy would
    # contribute zero anyway
    is_anchor = (lane == 0) | (lane == wcap)
    kept = (rank_w < Nw) & ~is_anchor[None, :]
    pos = r0[:, None].astype(jnp.uint32) + rank_w.astype(jnp.uint32)
    terms = mix32(hi, lo, jnp.where(kept, pos, 0), visible_w)
    window_sum = jnp.sum(
        jnp.where(kept, terms, jnp.uint32(0)), axis=1)
    digest = prefix_digest.astype(jnp.uint32) + window_sum
    return rank_w, visible_w, digest, overflow


@partial(jax.jit, donate_argnums=(0, 1))
def splice_ranks(rank_full, vis_full, rank_w, vis_w, starts, counts,
                 r0):
    """Splice a delta wave's window ranks/visibility into the resident
    full-width arrays (buffer-donated: updates in place on device).

    ``rank_full``/``vis_full`` are the [B, 2*cap] residents from the
    last wave; ``rank_w``/``vis_w`` the [B, 2*wcap] window outputs;
    ``starts[B, 2]`` each tree's shared-prefix length (the full-lane
    index of its first divergent lane), ``counts[B, 2]`` its divergent
    lane count, ``r0`` the [B] anchor rank. Window lane ``t*wcap+1+j``
    maps to full concat lane ``t*cap + starts[t] + j``; dropped window
    lanes (twin copies across the pair) splice the full-width sentinel
    ``2*cap``."""
    B, N = rank_full.shape
    cap = N // 2
    Nw = rank_w.shape[1]
    wcap = Nw // 2
    off = jnp.arange(wcap - 1, dtype=jnp.int32)

    def one_row(rf, vf, rw, vw, st, ct, r0_row):
        for t in range(2):
            src = t * wcap + 1 + off           # window D lanes
            w_rank = rw[src]
            w_vis = vw[src]
            val = jnp.where(w_rank < Nw,
                            r0_row.astype(jnp.int32) + w_rank,
                            jnp.int32(N))
            idx = t * cap + st[t] + off
            idx = jnp.where(off < ct[t], idx, N)  # beyond count: drop
            rf = rf.at[idx].set(val, mode="drop")
            vf = vf.at[idx].set(w_vis, mode="drop")
        return rf, vf

    return jax.vmap(one_row)(rank_full, vis_full, rank_w, vis_w,
                             starts, counts, r0)
