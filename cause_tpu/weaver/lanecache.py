"""Persistent per-handle device-lane caches.

The device marshal (``NodeArrays.from_nodes_map`` + ``tree_segments``)
used to be recomputed from the Python node dicts on every merge wave,
even though a tree's lanes and chain runs are a static per-tree fact
that each op changes only incrementally. The reference's whole design
is incremental caches — yarns and weave are maintained per-op and only
rebuilt from the bag of nodes on demand (shared.cljc:9-12,121-149);
this module gives the device lanes the same discipline:

- a ``LaneArena`` is an append-only structure-of-arrays store of one
  tree's marshalled lanes (the ``NodeArrays`` columns), shared across
  tree versions the way persistent vectors share tails: a ``LaneView``
  is ``(arena, n)`` and owning the arena tip lets an append extend in
  place (amortized O(k) per op); a non-tip extend copies first.
- appends are the common case by construction: a freshly minted node's
  lamport-ts exceeds every ts in the tree (``shared.insert`` fast-
  forwards the clock), so ``conj``/``extend``/``append`` always add
  lanes in ascending id order. Anything else — foreign mid-order
  inserts, wefts — drops the cache; the next device use rebuilds it
  lazily from the node dict (always correct, never stale: see
  ``CausalTree.evolve``, which clears ``lanes`` whenever ``nodes``
  changes without an explicit new cache).
- site-id ranks come from a per-collection-uuid ``SharedInterner``
  with *gapped* ranks, so every replica of one document in the process
  packs ids identically — a batched merge wave can ship cached lanes
  from many replicas straight into one kernel with no re-ranking —
  and a new site almost never disturbs existing ranks (it takes the
  midpoint of its neighbors' gap; only gap exhaustion forces a global
  reassignment, which bumps a generation stamp that invalidates
  stale-ranked arenas).
- per-view segment tables (``tree_segments``) are memoized on the
  arena, so a merge wave ships cached segment tables too.

The cache is only ever an accelerator: every consumer falls back to
``NodeArrays.from_nodes_map`` when a view is absent, stale, or outside
the PackSpec domain, and the invalidation fuzz suite asserts cached
lanes are indistinguishable from from-scratch lanes after arbitrary op
sequences (tests/test_lanecache.py).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional

import numpy as np

from .arrays import (
    DEFAULT_PACK,
    NodeArrays,
    PackSpec,
    vclass_of,
    next_pow2,
)
from ..ids import is_id
from ..obs import counter as _obs_counter, enabled as _obs_enabled

__all__ = [
    "SharedInterner",
    "interner_for",
    "LaneArena",
    "LaneView",
    "build_view",
    "extend_view",
    "view_for",
    "compatible",
    "shared_prefix_len",
    "union_views",
    "union_views_many",
]


_RANK_CEIL = (1 << DEFAULT_PACK.site_bits) - 1  # rank 2^18-1 is reserved
# (the all-ones lo packing is the padding sentinel, arrays.PackSpec)


class SharedInterner:
    """Order-preserving site-id -> rank map shared by every replica of
    one collection uuid in this process.

    Ranks are *gapped*: sites spread over the 18-bit rank space so a
    new site takes the midpoint of its neighbors' gap and existing
    assignments never move — which is what keeps independently grown
    replica caches mutually comparable (same string, same rank, in
    every arena). When a gap is exhausted all ranks are reassigned
    evenly and ``generation`` bumps; arenas stamped with an older
    generation re-rank lazily (their internal order stays valid — the
    reassignment is order-preserving — but they can no longer be mixed
    with fresh lanes in one kernel invocation).

    ``len()`` reports ``max_rank + 1`` so ``PackSpec.check``'s site
    bound covers the gapped layout, and ``NodeArrays``' one-past-the-
    end ghost rank stays collision-free.
    """

    __slots__ = ("sites", "rank", "generation", "max_rank", "_lock")

    def __init__(self):
        self.sites: List[str] = []
        self.rank: Dict[str, int] = {}
        self.generation = 0
        self.max_rank = -1  # cached: __len__ sits on the append hot path
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self.max_rank + 1

    def __contains__(self, site: str) -> bool:
        return site in self.rank

    def _reassign(self) -> None:
        # bump the generation BEFORE swapping the dict: a reader that
        # captures the new dict is then guaranteed to see the bumped
        # generation and bail (extend_view's capture-then-check), while
        # one that captured the old dict writes old-generation ranks
        # that its arena stamp still matches
        step = max(1, _RANK_CEIL // (len(self.sites) + 1))
        self.generation += 1
        self.rank = {s: (i + 1) * step for i, s in enumerate(self.sites)}
        self.max_rank = len(self.sites) * step

    def ensure(self, sites) -> int:
        """Intern any missing sites; returns the (possibly bumped)
        generation."""
        missing = sorted(set(s for s in sites if s not in self.rank))
        if not missing:
            return self.generation
        with self._lock:
            for s in missing:
                if s in self.rank:
                    continue
                pos = bisect.bisect_left(self.sites, s)
                lo = self.rank[self.sites[pos - 1]] if pos > 0 else -1
                hi = (
                    self.rank[self.sites[pos]]
                    if pos < len(self.sites)
                    else _RANK_CEIL
                )
                mid = (lo + hi) // 2
                self.sites.insert(pos, s)
                if mid <= lo or mid >= hi:
                    self._reassign()  # gap exhausted: spread + new gen
                else:
                    self.rank[s] = mid
                    if mid > self.max_rank:
                        self.max_rank = mid
        return self.generation


_REGISTRY: Dict[str, SharedInterner] = {}
_REGISTRY_LOCK = threading.Lock()
_REGISTRY_CAP = 4096


def interner_for(uuid: str) -> SharedInterner:
    """The process-wide shared interner of one collection uuid."""
    it = _REGISTRY.get(uuid)
    if it is None:
        with _REGISTRY_LOCK:
            it = _REGISTRY.get(uuid)
            if it is None:
                if len(_REGISTRY) >= _REGISTRY_CAP:
                    # drop ~half, oldest-inserted first (dict order);
                    # evicted uuids simply mint a fresh interner (their
                    # existing arenas keep a reference and stay valid)
                    for k in list(_REGISTRY)[: _REGISTRY_CAP // 2]:
                        del _REGISTRY[k]
                it = SharedInterner()
                _REGISTRY[uuid] = it
    return it


def _seg_cache_put(cache: dict, n: int, segs) -> None:
    """Shared bounded-insert policy for arena segment caches (callers
    hold whatever locking they need)."""
    if len(cache) >= 4:
        try:
            cache.pop(min(cache))
        except (ValueError, KeyError):
            pass  # concurrent evictor got there first
    cache[n] = segs


class LaneArena:
    """Append-only lane arena shared by successive versions of one
    tree. ``committed_n`` is the arena tip: a view owning the tip may
    extend in place; any other extension copies into a fresh arena
    first (so sibling branches can never see each other's lanes)."""

    __slots__ = (
        "ts", "site", "tx", "cause_idx", "vclass", "cause_hi", "cause_lo",
        "nodes", "lane_of", "interner", "generation", "spec",
        "committed_n", "seg_cache", "lock",
    )

    def __init__(self, ts, site, tx, cause_idx, vclass, cause_hi, cause_lo,
                 nodes, lane_of, interner, generation, spec, committed_n):
        self.ts = ts
        self.site = site
        self.tx = tx
        self.cause_idx = cause_idx
        self.vclass = vclass
        self.cause_hi = cause_hi
        self.cause_lo = cause_lo
        self.nodes = nodes          # list of (id, cause, value), lane order
        self.lane_of = lane_of      # {id: lane}
        self.interner = interner
        self.generation = generation
        self.spec = spec
        self.committed_n = committed_n
        self.seg_cache = {}         # {n: tree_segments result}
        self.lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return int(self.ts.shape[0])

    def sync_ranks(self) -> None:
        """Upgrade this arena in place after an interner rank
        reassignment. Reassignment is order-preserving, so only the
        site lane and the packed cause-lo lane carry stale VALUES —
        one vectorized rewrite each brings every view over this arena
        back into the current generation (no rebuild, no drop). The
        memoized segment tables embed packed ids, so they clear."""
        it = self.interner
        if self.generation == it.generation:
            return
        with self.lock:
            with it._lock:  # consistent (generation, rank) snapshot;
                # ensure() never takes an arena lock, so no cycle
                gen = it.generation
                rank = it.rank
            if self.generation == gen:
                return
            n = self.committed_n
            self.site[:n] = np.fromiter(
                (rank[nd[0][1]] for nd in self.nodes[:n]), np.int64, n
            )
            has_c = self.cause_idx[:n] >= 0
            ci = np.clip(self.cause_idx[:n], 0, max(0, n - 1))
            self.cause_lo[:n] = np.where(
                has_c,
                self.spec.pack_lo(self.site[:n][ci], self.tx[:n][ci]),
                self.cause_lo[:n],
            )
            # dangling id causes (no lane to gather from): re-pack off
            # the host cause tuple — rare, weft-gibberish only
            dang = (self.cause_hi[:n] >= 0) & ~has_c
            if dang.any():
                ghost = len(it)
                for i in np.flatnonzero(dang):
                    cz = self.nodes[i][1]
                    self.cause_lo[i] = self.spec.pack_lo(
                        np.int32(rank.get(cz[1], ghost)), np.int32(cz[2])
                    )
            self.seg_cache.clear()
            self.generation = gen


class LaneView:
    """An immutable (arena, n) snapshot — the ``lanes`` cache slot of
    one ``CausalTree`` version."""

    __slots__ = ("arena", "n")

    def __init__(self, arena: LaneArena, n: int):
        self.arena = arena
        self.n = n

    @property
    def generation(self) -> int:
        return self.arena.generation

    @property
    def interner(self) -> SharedInterner:
        return self.arena.interner

    def node_arrays(self) -> NodeArrays:
        """A ``NodeArrays`` over this view. Lanes at or beyond ``n``
        may hold a newer version's data in the shared arena, so every
        column is masked to the view (cheap vectorized copies)."""
        self.arena.sync_ranks()
        a, n, cap = self.arena, self.n, self.arena.capacity
        valid = np.zeros(cap, bool)
        valid[:n] = True
        return NodeArrays(
            ts=np.where(valid, a.ts, 0),
            site=np.where(valid, a.site, 0),
            tx=np.where(valid, a.tx, 0),
            cause_idx=np.where(valid, a.cause_idx, -1),
            vclass=np.where(valid, a.vclass, 0),
            valid=valid,
            cause_hi=np.where(valid, a.cause_hi, -1),
            cause_lo=np.where(valid, a.cause_lo, -1),
            nodes=a.nodes[:n],
            interner=a.interner,
            n=n,
            spec=a.spec,
            spec_ok=True,
        )

    def segments(self, na: Optional[NodeArrays] = None):
        """Memoized ``tree_segments`` of this view (the per-tree chain
        tables the v5 kernel unions). Pass the ``node_arrays()`` you
        already built to skip re-masking the columns on a miss."""
        segs = self.arena.seg_cache.get(self.n)
        if segs is None:
            _obs_counter("lanecache.segments.miss").inc()
            from .segments import tree_segments

            if na is None:
                na = self.node_arrays()
            hi, lo = na.id_lanes()
            segs = tree_segments(hi, lo, na.cause_idx, na.vclass, na.n)
            with self.arena.lock:
                _seg_cache_put(self.arena.seg_cache, self.n, segs)
        else:
            _obs_counter("lanecache.segments.hit").inc()
        return segs


def _arena_from_node_arrays(na: NodeArrays, interner: SharedInterner,
                            generation: int) -> LaneArena:
    return LaneArena(
        ts=na.ts.copy(), site=na.site.copy(), tx=na.tx.copy(),
        cause_idx=na.cause_idx.copy(), vclass=na.vclass.copy(),
        cause_hi=na.cause_hi.copy(), cause_lo=na.cause_lo.copy(),
        nodes=list(na.nodes),
        lane_of={nid: i for i, (nid, _, _) in enumerate(na.nodes)},
        interner=interner, generation=generation, spec=na.spec,
        committed_n=na.n,
    )


def build_view(nodes_map: dict, uuid: str,
               spec: PackSpec = DEFAULT_PACK) -> Optional[LaneView]:
    """Marshal a node dict into a fresh cached view (shared-interner
    ranks). Returns None when the ids are outside the PackSpec domain
    — callers keep their existing from-scratch fallbacks."""
    interner = interner_for(uuid)
    gen = interner.ensure(nid[1] for nid in nodes_map)
    na = NodeArrays.from_nodes_map(
        nodes_map, capacity=next_pow2(len(nodes_map)),
        interner=interner, spec=spec,
    )
    if not na.spec_ok:
        return None
    view = LaneView(_arena_from_node_arrays(na, interner, gen), na.n)
    if _obs_enabled():
        # devprof host-memory telemetry: a from-scratch marshal is the
        # expensive rebuild path, so its arena footprint is the curve
        # that shows fleet-cache growth in a trace
        from ..obs import devprof as _devprof

        _devprof.arena_footprint(view.arena, site="lanecache.build")
    return view


def _copy_arena(view: LaneView, min_capacity: int) -> LaneArena:
    a, n = view.arena, view.n
    cap = next_pow2(min_capacity)

    def grow(arr, fill):
        out = np.full(cap, fill, arr.dtype)
        out[:n] = arr[:n]
        return out

    return LaneArena(
        ts=grow(a.ts, 0), site=grow(a.site, 0), tx=grow(a.tx, 0),
        cause_idx=grow(a.cause_idx, -1), vclass=grow(a.vclass, 0),
        cause_hi=grow(a.cause_hi, -1), cause_lo=grow(a.cause_lo, -1),
        nodes=a.nodes[:n],
        lane_of={nid: i for i, (nid, _, _) in enumerate(a.nodes[:n])},
        interner=a.interner, generation=a.generation, spec=a.spec,
        committed_n=n,
    )


def extend_view(view: Optional[LaneView], new_nodes) -> Optional[LaneView]:
    """Append freshly inserted nodes to a cached view.

    Applies only to the append fast path: every new id must exceed the
    view's tail id and arrive in ascending order (what ``conj`` /
    ``extend`` / ``append`` mint, since the lamport clock fast-forwards
    past every known ts). Anything else — mid-order foreign inserts, a
    site whose interning reassigned ranks, ids beyond the PackSpec —
    returns None and the cache is simply dropped (rebuilt lazily).
    """
    if view is None:
        return None
    # attempt/append counters: the gap between them is the bail rate
    # (cache drops that force a lazy rebuild) — the signal the round-3
    # incremental-marshal work exists to keep near zero
    _obs_counter("lanecache.extend.attempt").inc()
    arena = view.arena
    interner = arena.interner
    arena.sync_ranks()  # a rank reassignment upgrades in place
    n = view.n
    tail = arena.nodes[n - 1][0] if n > 0 else None
    prev = tail
    for nd in new_nodes:
        if prev is not None and nd[0] <= prev:
            return None
        prev = nd[0]
    gen = interner.ensure(nd[0][1] for nd in new_nodes)
    if gen != arena.generation:
        return None
    k = len(new_nodes)
    spec = arena.spec
    try:
        spec.check(
            max(nd[0][0] for nd in new_nodes),
            len(interner),
            max(max(nd[0][2] for nd in new_nodes),
                max((nd[1][2] for nd in new_nodes if is_id(nd[1])),
                    default=0)),
        )
    except OverflowError:
        return None

    # resolve every id cause BEFORE mutating anything (a mid-append
    # bail would leave the arena corrupt). The shared lane_of may hold
    # a sibling branch's lanes at index >= n — those are NOT ours.
    pos = {nd[0]: n + j for j, nd in enumerate(new_nodes)}
    cause_lane = []
    for nd in new_nodes:
        c = nd[1]
        if is_id(c):
            c = tuple(c)
            ci = pos.get(c)
            if ci is None:
                ci = arena.lane_of.get(c)
                if ci is None or ci >= n:
                    return None  # dangling / foreign-branch cause
            cause_lane.append(ci)
        else:
            cause_lane.append(-1)

    with arena.lock:
        if arena.committed_n != n or n + k > arena.capacity:
            arena = _copy_arena(view, n + k)
        # capture-then-check: a concurrent gap-exhaustion reassignment
        # swaps the rank dict after bumping the generation, so a rank
        # dict captured under a still-matching generation is guaranteed
        # to carry this arena's generation of ranks
        rank = interner.rank
        if interner.generation != arena.generation:
            return None
        lane_of = arena.lane_of
        i = n
        for (nid, cause, value), ci in zip(new_nodes, cause_lane):
            arena.ts[i] = nid[0]
            arena.site[i] = rank[nid[1]]
            arena.tx[i] = nid[2]
            arena.vclass[i] = vclass_of(value)
            arena.cause_idx[i] = ci
            if ci >= 0:
                arena.cause_hi[i] = cause[0]
                arena.cause_lo[i] = spec.pack_lo(
                    np.int32(rank.get(cause[1], len(interner))),
                    np.int32(cause[2]),
                )
            else:
                arena.cause_hi[i] = -1
                arena.cause_lo[i] = -1
            arena.nodes.append((nid, cause, value))
            lane_of[nid] = i
            i += 1
        arena.committed_n = n + k
        # extend the memoized segment tables in O(k) when the append
        # shape allows (segments.extend_segments); a bail just leaves
        # the next device use to recompute lazily
        old_segs = arena.seg_cache.get(n)
        if old_segs is not None:
            from .segments import extend_segments

            lo_win = spec.pack_lo(arena.site[n - 1: n + k],
                                  arena.tx[n - 1: n + k])
            new_segs = extend_segments(
                old_segs, arena.ts, lo_win, arena.cause_idx,
                arena.vclass, n, n + k,
            )
            if new_segs is not None:
                _seg_cache_put(arena.seg_cache, n + k, new_segs)
    _obs_counter("lanecache.extend.append").inc()
    return LaneView(arena, n + k)


def _list_shaped_types():
    """Tree types whose lanes ARE list lanes (maps need the key-rooted
    forest encoding of weaver.mapw instead). Derived from the type
    constants so a rename can't silently diverge."""
    from ..collections.ccounter import COUNTER_TYPE
    from ..collections.cset import SET_TYPE
    from ..collections.shared import LIST_TYPE

    return frozenset((LIST_TYPE, SET_TYPE, COUNTER_TYPE))


LIST_SHAPED: frozenset = None  # populated lazily (import-cycle safety)


def view_for(ct) -> Optional[LaneView]:
    """The tree's cached view if fresh, else a new build — LIST-SHAPED
    trees only: a map tree through these lanes would mint a
    list-semantics weave, so it returns None and callers take their
    fallback/mapw path. None also when the tree is outside the
    cacheable domain (PackSpec overflow)."""
    global LIST_SHAPED
    if LIST_SHAPED is None:
        LIST_SHAPED = _list_shaped_types()
    if ct.type not in LIST_SHAPED:
        _obs_counter("lanecache.view.unshaped").inc()
        return None
    view = getattr(ct, "lanes", None)
    if isinstance(view, LaneView) and view.n == len(ct.nodes):
        _obs_counter("lanecache.view.hit").inc()
        return view
    _obs_counter("lanecache.view.rebuild").inc()
    return build_view(ct.nodes, ct.uuid)


def compatible(views) -> bool:
    """Whether these views' lanes are directly comparable in one kernel
    invocation: same shared interner object, same rank generation
    (stale arenas are upgraded in place first — see sync_ranks)."""
    views = [v for v in views if v is not None]
    if not views:
        return False
    it = views[0].interner
    for v in views:
        if v.interner is not it:
            return False
        v.arena.sync_ranks()
    gen = it.generation
    return all(v.generation == gen for v in views)


def _packed_keys(a: LaneArena, n: int) -> np.ndarray:
    lo = a.spec.pack_lo(a.site[:n], a.tx[:n])
    return (a.ts[:n].astype(np.int64) << 32) | (
        lo.astype(np.int64) & 0xFFFFFFFF
    )


def shared_prefix_len(va: LaneView, vb: LaneView) -> int:
    """Length of the leading lane range holding IDENTICAL node ids in
    both views — the converged resident prefix of a replica pair, the
    quantity the delta-native wave pins its frozen region to. Lanes
    are id-sorted, so one vectorized packed-key compare finds the
    first divergence point. Views must be ``compatible`` (same rank
    generation) or the packed site ranks would not be comparable;
    the delta-session caller guarantees that."""
    n = min(va.n, vb.n)
    if n <= 0:
        return 0
    ka = _packed_keys(va.arena, n)
    kb = _packed_keys(vb.arena, n)
    eq = ka == kb
    if eq.all():
        return n
    return int(np.argmin(eq))


def union_views(va: LaneView, vb: LaneView) -> Optional[LaneView]:
    """Vectorized union of two cached views into a fresh view over the
    merged node set (see ``union_views_many``)."""
    return union_views_many((va, vb))


def union_views_many(views) -> Optional[LaneView]:
    """Vectorized K-way union of cached views into a fresh view over
    the merged node set — the marshal half of an accelerated merge
    with NO per-node Python loop and no dict sort: one packed-key
    argsort of every view's concatenated lanes, adjacent-duplicate
    drop, and one searchsorted pass to re-resolve causes against the
    union. Requires ``compatible`` views (same interner generation, or
    the packed keys would not be comparable); body conflicts between
    duplicate ids are NOT checked here — callers run the append-only
    union validation (shared.union_nodes semantics) before trusting
    the result."""
    views = list(views)
    if not views or not compatible(views):
        return None
    arenas = [v.arena for v in views]
    ns = [v.n for v in views]
    keys = np.concatenate([
        _packed_keys(a, n) for a, n in zip(arenas, ns)
    ])
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    dup = np.zeros(len(ks), bool)
    dup[1:] = ks[1:] == ks[:-1]
    kept = order[~dup]
    n = len(kept)
    cap = next_pow2(n)

    def col(name, fill):
        src = np.concatenate([
            getattr(a, name)[:cnt] for a, cnt in zip(arenas, ns)
        ])
        out = np.full(cap, fill, src.dtype)
        out[:n] = src[kept]
        return out

    ts = col("ts", 0)
    site = col("site", 0)
    tx = col("tx", 0)
    vclass = col("vclass", 0)
    cause_hi = col("cause_hi", -1)
    cause_lo = col("cause_lo", -1)
    # re-resolve causes against the union's packed keys
    union_keys = ks[~dup]
    q = (cause_hi[:n].astype(np.int64) << 32) | (
        cause_lo[:n].astype(np.int64) & 0xFFFFFFFF
    )
    posq = np.searchsorted(union_keys, q)
    posc = np.clip(posq, 0, max(0, n - 1))
    found = (cause_hi[:n] >= 0) & (n > 0) & (union_keys[posc] == q)
    cause_idx = np.full(cap, -1, np.int32)
    cause_idx[:n] = np.where(found, posc, -1)

    # map each kept concat position back to its source (view, lane)
    bounds = np.cumsum([0] + ns)
    src_view = np.searchsorted(bounds, kept, side="right") - 1
    src_lane = kept - bounds[src_view]
    node_lists = [a.nodes for a in arenas]
    nodes = [
        node_lists[int(v)][int(i)] for v, i in zip(src_view, src_lane)
    ]
    arena = LaneArena(
        ts=ts, site=site, tx=tx, cause_idx=cause_idx, vclass=vclass,
        cause_hi=cause_hi, cause_lo=cause_lo, nodes=nodes,
        lane_of={nid: i for i, (nid, _, _) in enumerate(nodes)},
        interner=arenas[0].interner, generation=views[0].generation,
        spec=arenas[0].spec, committed_n=n,
    )
    return LaneView(arena, n)
