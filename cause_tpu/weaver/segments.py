"""Marshal-side segment extraction for the v5 segment-union kernel.

A causal tree's chain-run structure is a *static per-tree fact*: runs
are maximal stretches of lanes where each node's cause is the previous
lane and the v4 glue rules hold locally (no host-case, parent not
contested). ``NodeArrays`` lanes are id-sorted, so every run is a
contiguous lane range — which means a merge can treat a whole run as
ONE sort token whenever nothing foreign intrudes on it, and only
explode to node granularity where replicas actually diverged. That is
the right asymptotic for a CRDT: merge cost scales with the
divergence, not the document size (the reference pays O(n*m) on the
whole tree, shared.cljc:300-314).

This module computes, per tree, host-side (vectorized numpy — one pass
over the lanes, same cost class as building the lanes themselves):

- ``run_of_lane``: each lane's segment ordinal;
- per-segment tables: head lane, length, head id (= min id), tail id
  (= max id), a *dense* flag (member ids fully determined by
  (min, max, len): consecutive-ts conj chains or same-ts tx-index runs
  — the shapes ``conj`` and ``extend`` mint), and whether the tail is
  special (trailing tombstone chain);
- the root is always forced into its own singleton segment so the
  root+base prefix shared by every replica stays wholesale-dedupable
  (the root id's packed lo differs from the chain site's, which would
  otherwise break the dense test).

Segmentation MUST mirror ``jaxw4``'s local glue semantics exactly —
the device kernel re-glues *tokens* with the same rules, so local runs
have to be unions of v4 runs for the expansion to agree. The
correspondence is fuzz-tested against the device kernels.
"""

from __future__ import annotations

import math
from typing import Dict

import numpy as np

__all__ = [
    "tree_segments",
    "extend_segments",
    "concat_segments",
    "SEG_KEYS",
    "SEG_LANE_KEYS",
]

SEG_KEYS = (
    "sg_head_lane",  # lane of the segment head (tree coordinates)
    "sg_len",        # member count
    "sg_min_hi", "sg_min_lo",   # head id (the minimum member id)
    "sg_max_hi", "sg_max_lo",   # tail id (the maximum member id)
    "sg_dense",      # member ids determined by (min, max, len): either
                     # (hi..hi+len-1, constant lo) conj chains or
                     # (constant hi, lo..lo+len-1) tx runs; dedupe ok
    "sg_tail_special",  # tail lane carries a special (tombstone suffix)
    "sg_vsum",       # position-weighted vclass checksum of the members:
                     # sum((i+1) * vclass). Twin dedupe compares it so a
                     # same-id segment whose INTERIOR body classes differ
                     # (append-only violation from a corrupt replica)
                     # explodes and hits the node-level conflict check
                     # instead of vanishing wholesale. Host VALUES stay a
                     # host-side check — the device never sees them.
)

# the device kernel's segment-table lanes (concat coordinates, padded)
SEG_LANE_KEYS = (
    "sg_min_hi", "sg_min_lo", "sg_max_hi", "sg_max_lo",
    "sg_len", "sg_lane0", "sg_dense", "sg_tail_special", "sg_valid",
    "sg_vsum",
)


def tree_segments(hi, lo, cause_idx, vclass, n: int) -> Dict[str, np.ndarray]:
    """Segment one tree's lanes (ascending id order, lane 0 = root).

    Returns ``run_of_lane`` ([capacity] int32, -1 beyond ``n``) plus the
    ``SEG_KEYS`` tables (length = number of segments). Mirrors the v4
    union kernel's glue computation restricted to a single tree:
    ``glued[i] = adj & ~host_case & ~contested[i-1]`` with parents
    resolved through the special-chain host jump.
    """
    cap = hi.shape[0]
    run_of_lane = np.full(cap, -1, np.int32)
    if n <= 0:
        return {
            "run_of_lane": run_of_lane,
            **{k: np.zeros(0, np.int32) for k in SEG_KEYS},
        }

    idx = np.arange(n, dtype=np.int32)
    special = vclass[:n] > 0
    adj = np.zeros(n, bool)
    adj[1:] = cause_idx[1:n] == idx[:-1]
    host_case = adj & ~special
    host_case[1:] &= special[:-1]
    host_case[0] = False
    irregular = (idx > 0) & (~adj | host_case)

    # local parents: specials hang off their cause, non-specials off the
    # first non-special ancestor through the cause chain
    cs = np.clip(cause_idx[:n], 0, n - 1)
    host = cs.copy()
    for _ in range(max(1, math.ceil(math.log2(max(2, n))))):
        on_special = special[host] & (idx > 0)
        if not on_special.any():
            break
        host = np.where(on_special, host[host], host)
    parent = np.where(idx > 0, np.where(special, cs, host), -1)

    # contested: lanes that parent at least one irregular child
    contested = np.zeros(n, bool)
    ip = parent[irregular]
    contested[ip[ip >= 0]] = True

    glued = adj & ~host_case
    glued[1:] &= ~contested[:-1]
    glued[0] = False
    # split at density breaks (site change or ts jump): the dedupable
    # unit is the dense run, and density breaks are exactly where a
    # shared prefix flows into site-local edits — without the split,
    # the shared base would glue into the divergent suffix and lose
    # its wholesale-dedupe (the union kernel re-glues tokens, so extra
    # boundaries never change the final weave). TWO dense patterns:
    # consecutive-ts conj chains (hi+1, lo constant) and same-tx extend
    # runs (hi constant, lo+1 — one transaction's tx-index run, the
    # API's bulk paste path, list.cljc:23-25 analogue)
    dense_hi = np.zeros(n, bool)
    dense_lo = np.zeros(n, bool)
    dense_hi[1:] = (lo[1:n] == lo[: n - 1]) & (hi[1:n] == hi[: n - 1] + 1)
    dense_lo[1:] = (hi[1:n] == hi[: n - 1]) & (lo[1:n] == lo[: n - 1] + 1)
    dense_ok = dense_hi | dense_lo
    dense_ok[0] = True
    glued &= dense_ok
    # the root is always a singleton segment (its packed lo differs
    # from any chain site's, so a root-headed run could never be
    # dense). This must precede the alternation cut: the cut reads
    # glued[1], and the pre-singleton value depends on whether the
    # ROOT is contested — which later root-caused lanes flip, making
    # old segment boundaries depend on the tree's future (raw fuzz
    # caught exactly that prefix instability).
    if n > 1:
        glued[1] = False
    # dedupe soundness: a dense run's member ids must be fully
    # determined by (min, max, len), which holds only when the whole
    # run follows ONE pattern (for len > 1 the endpoints reveal which:
    # exactly one of max_hi == min_hi / max_lo == min_lo). Cut the
    # second of any two consecutive glued pairs whose patterns differ.
    if n > 2:
        alt = np.zeros(n, bool)
        alt[2:] = glued[2:] & glued[1:-1] & (dense_lo[2:] != dense_lo[1:-1])
        glued &= ~alt

    run_start = ~glued
    rid = np.cumsum(run_start).astype(np.int32) - 1
    run_of_lane[:n] = rid
    n_runs = int(rid[-1]) + 1

    head_lane = np.flatnonzero(run_start).astype(np.int32)
    nxt = np.concatenate([head_lane[1:], np.int32([n])])
    sg_len = (nxt - head_lane).astype(np.int32)
    tail_lane = nxt - 1

    sg_min_hi = hi[:n][head_lane].astype(np.int32)
    sg_min_lo = lo[:n][head_lane].astype(np.int32)
    sg_max_hi = hi[:n][tail_lane].astype(np.int32)
    sg_max_lo = lo[:n][tail_lane].astype(np.int32)

    # dense: every adjacent pair follows one of the two dense patterns
    # (hi+1/lo-const conj chains or hi-const/lo+1 tx runs), uniform
    # along the run via the alternation cut above. The glue split makes
    # every multi-lane run dense by construction; keep the aggregate
    # check anyway (robustness against a future glue-rule change
    # silently losing the invariant)
    bad = ~dense_ok & ~run_start  # the head lane never breaks its run
    bad_runs = np.zeros(n_runs, bool)
    bad_runs[rid[bad]] = True
    sg_dense = ~bad_runs

    sg_tail_special = special[tail_lane]

    # position-weighted vclass checksum per run: catches interior body
    # -class divergence between same-id twins (see SEG_KEYS). int64
    # accumulate + 31-bit mask: bincount's float64 path would make the
    # int32 cast platform-dependent for very long special runs, and the
    # checksum only needs deterministic equality
    offset = idx - head_lane[rid[:n]]
    vsum64 = np.zeros(n_runs, np.int64)
    np.add.at(vsum64, rid[:n],
              (offset.astype(np.int64) + 1) * vclass[:n])
    sg_vsum = (vsum64 & 0x7FFFFFFF).astype(np.int32)

    return {
        "run_of_lane": run_of_lane,
        "sg_head_lane": head_lane,
        "sg_len": sg_len,
        "sg_min_hi": sg_min_hi,
        "sg_min_lo": sg_min_lo,
        "sg_max_hi": sg_max_hi,
        "sg_max_lo": sg_max_lo,
        "sg_dense": sg_dense.astype(bool),
        "sg_tail_special": sg_tail_special.astype(bool),
        "sg_vsum": sg_vsum,
    }


_TABLE_DTYPES = {
    "sg_min_hi": np.int32, "sg_min_lo": np.int32,
    "sg_max_hi": np.int32, "sg_max_lo": np.int32,
    "sg_len": np.int32, "sg_lane0": np.int32,
    "sg_dense": bool, "sg_tail_special": bool,
    "sg_valid": bool, "sg_vsum": np.int32,
}


def concat_seg_tables(per_tree, capacity: int, s_max: int,
                      out: Dict[str, np.ndarray] = None):
    """Fill the ``SEG_LANE_KEYS`` table arrays for one concat row —
    the single place that knows the layout (wave assembly, delta
    sessions, and ``concat_segments`` all route through it). ``out``
    may carry preallocated [s_max] arrays (e.g. batch-row views);
    entries beyond each tree's tables are zeroed/invalidated. Returns
    ``(out, bases)`` with each tree's starting segment ordinal."""
    if out is None:
        out = {k: np.zeros(s_max, dt) for k, dt in _TABLE_DTYPES.items()}
    bases = []
    base = 0
    for t, (segs, _n) in enumerate(per_tree):
        k = segs["sg_len"].shape[0]
        if base + k > s_max:
            raise OverflowError(
                f"segment budget {s_max} < {base + k} segments"
            )
        sl = slice(base, base + k)
        out["sg_min_hi"][sl] = segs["sg_min_hi"]
        out["sg_min_lo"][sl] = segs["sg_min_lo"]
        out["sg_max_hi"][sl] = segs["sg_max_hi"]
        out["sg_max_lo"][sl] = segs["sg_max_lo"]
        out["sg_len"][sl] = segs["sg_len"]
        out["sg_lane0"][sl] = segs["sg_head_lane"] + t * capacity
        out["sg_dense"][sl] = segs["sg_dense"]
        out["sg_tail_special"][sl] = segs["sg_tail_special"]
        out["sg_vsum"][sl] = segs["sg_vsum"]
        out["sg_valid"][sl] = True
        bases.append(base)
        base += k
    if base < s_max:  # invalidate any leftover tail (reused buffers)
        tail = slice(base, s_max)
        out["sg_valid"][tail] = False
        out["sg_len"][tail] = 0
    return out, bases


def concat_segments(per_tree, capacity: int, s_max: int) -> Dict[str, np.ndarray]:
    """Assemble per-tree segment tables into the device kernel's concat
    layout: ``per_tree`` is a list of (``tree_segments`` result, n)
    tuples, each tree occupying ``capacity`` concat lanes in order.

    Returns the ``SEG_LANE_KEYS`` arrays padded to ``s_max`` (in lane
    order — marshal order IS ascending concat lane order, which the
    kernel's expansion scans rely on) plus ``seg`` ([n_trees*capacity]
    int32): every concat lane's segment ordinal (-1 padding).
    """
    n_trees = len(per_tree)
    out, bases = concat_seg_tables(per_tree, capacity, s_max)
    seg = np.full(n_trees * capacity, -1, np.int32)
    for t, ((segs, n), base) in enumerate(zip(per_tree, bases)):
        rl = segs["run_of_lane"]
        lane_sl = slice(t * capacity, t * capacity + n)
        seg[lane_sl] = rl[:n] + base
    out["seg"] = seg
    return out


def extend_segments(segs, hi, lo_win, cause_idx, vclass, n_old: int,
                    n_new: int):
    """O(k) extension of a tree's segment tables for appended lanes
    ``[n_old, n_new)`` — the segment twin of the lane cache's append
    fast path (a 10k-tree ``tree_segments`` costs ~1 ms; a sync fleet
    recomputing it per edited replica per wave pays seconds).

    ``hi``/``cause_idx``/``vclass`` are full arena columns (free);
    ``lo_win`` covers lanes ``[n_old-1, n_new)`` only, so the caller
    never packs the whole tree. Returns the new tables, or None when
    the append shape needs a full recompute. The *simple-append
    domain* (everything conj/extend/cons/tail-tombstones mint):

    - every appended cause resolves to the appended chain (i-1), the
      old tail (n_old-1), the root (0), or nothing (-1);
    - a non-special appended whose host jump would walk past a SPECIAL
      old tail into old lanes is out.

    Within that domain OLD glue bits cannot change: new children
    attach only to the old tail (whose contestedness affects only lane
    n_old's glue) or the root (always a singleton) — so the old tables
    survive verbatim except that the LAST segment may extend, and the
    appended lanes segment locally. Fuzz-checked against from-scratch
    ``tree_segments`` (tests/test_lanecache.py).
    """
    k = n_new - n_old
    n_segs_old = segs["sg_len"].shape[0]
    if n_old < 2 or k <= 0 or n_segs_old == 0:
        return None

    def LO(lane):
        return lo_win[lane - (n_old - 1)]

    idx = np.arange(n_old, n_new, dtype=np.int64)
    ci = cause_idx[n_old:n_new].astype(np.int64)
    special = vclass[n_old:n_new] > 0
    chain = ci == idx - 1          # includes the boundary lane n_old
    to_tail = ci == n_old - 1
    to_root = ci == 0
    none_c = ci == -1
    if not bool(np.all(chain | to_tail | to_root | none_c)):
        return None  # stabs an old interior lane: recompute
    old_tail_special = bool(vclass[n_old - 1] > 0)

    # parents (for contestedness): specials hang off their cause,
    # non-specials off the first non-special through the chain. -2
    # stands for root/none (harmless: their glue is already fixed).
    parent = np.full(k, -2, np.int64)
    for j in range(k):
        if special[j]:
            c = ci[j]
            parent[j] = c if c >= n_old - 1 else -2
            continue
        p = ci[j]
        while p >= n_old and vclass[int(p)] > 0:
            p = cause_idx[int(p)]
        if p == n_old - 1 and old_tail_special:
            return None  # host walk would continue into old lanes
        if p >= n_old - 1:
            parent[j] = p
        else:
            parent[j] = -2

    prev_special = np.concatenate([[old_tail_special], special[:-1]])
    adj = chain
    host_case = adj & ~special & prev_special
    irregular = ~adj | host_case
    contested = set(int(p) for p in parent[irregular] if p >= 0)
    prev_contested = np.fromiter(
        (int(p) in contested for p in idx - 1), bool, k
    )
    lo_cur = lo_win[1:]
    lo_prev = lo_win[:-1]
    hi_cur = hi[n_old:n_new]
    hi_prev = hi[n_old - 1:n_new - 1]
    dense_hi_p = (lo_cur == lo_prev) & (hi_cur == hi_prev + 1)
    dense_lo_p = (hi_cur == hi_prev) & (lo_cur == lo_prev + 1)
    glued = adj & ~host_case & ~prev_contested & (dense_hi_p | dense_lo_p)
    pat = dense_lo_p

    # boundary pattern consistency with the old last segment
    old_len = int(segs["sg_len"][-1])
    if glued[0] and old_len > 1:
        old_lo_pat = bool(segs["sg_max_hi"][-1] == segs["sg_min_hi"][-1])
        if bool(pat[0]) != old_lo_pat:
            glued[0] = False
    for j in range(1, k):  # alternation cut within the appended run
        if glued[j] and glued[j - 1] and bool(pat[j]) != bool(pat[j - 1]):
            glued[j] = False

    # run ids for the appended lanes
    last = n_segs_old - 1
    rid = np.empty(k, np.int64)
    cur = last
    new_heads = []
    for j in range(k):
        if not glued[j]:
            cur += 1
            new_heads.append((cur, n_old + j))
        rid[j] = cur
    n_segs_new = cur + 1

    rol = segs["run_of_lane"]
    if n_new > rol.shape[0]:
        grown = np.full(max(n_new, 2 * rol.shape[0]), -1, np.int32)
        grown[: rol.shape[0]] = rol
        rol = grown
    else:
        rol = rol.copy()
    rol[n_old:n_new] = rid.astype(np.int32)

    out = {"run_of_lane": rol}
    for key in SEG_KEYS:
        grow = np.zeros(n_segs_new, segs[key].dtype)
        grow[:n_segs_old] = segs[key]
        out[key] = grow
    for sg, head in new_heads:
        out["sg_head_lane"][sg] = head
        out["sg_min_hi"][sg] = hi[head]
        out["sg_min_lo"][sg] = LO(head)
        out["sg_dense"][sg] = True  # glue requires a dense pattern
    # per-touched-segment tails/lengths/checksums
    for sg in range(last, n_segs_new):
        mask = rid == sg
        c = int(mask.sum())
        if c == 0:
            continue  # the old last segment gained nothing
        lanes = np.flatnonzero(mask) + n_old
        tail = int(lanes[-1])
        base_len = int(out["sg_len"][sg]) if sg == last else 0
        out["sg_len"][sg] = base_len + c
        out["sg_max_hi"][sg] = hi[tail]
        out["sg_max_lo"][sg] = LO(tail)
        out["sg_tail_special"][sg] = bool(vclass[tail] > 0)
        w = (base_len + np.arange(1, c + 1, dtype=np.int64)) * vclass[lanes]
        out["sg_vsum"][sg] = np.int32(
            (int(out["sg_vsum"][sg]) + int(w.sum())) & 0x7FFFFFFF
        )
    return out
