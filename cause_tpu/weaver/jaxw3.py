"""The v3 merge+weave kernel: sparse-irregular linearization.

Profiling the v2 pipeline on a TPU v5e (scripts/profile_phases.py,
1024 pairs x 10k nodes) showed the cost is NOT the id sort (~97 ms);
it is every gather/scatter/sort pass that runs at full lane width:
the 2M-record sort-join cause resolution (~5.3 s), the full-width
pointer-doubling host jump (~1.9 s), and the full-width visibility
scatter+gather (~0.7 s). TPUs stream contiguous tiles superbly but
pay dearly for random access — so v3 restructures the whole merge so
that full-width work is *elementwise and scan only*, and every random
access (binary search, gather, scatter, sort) happens at the
chain-compressed run width K (~2k for the north-star workload,
a 10,000x narrower access stream):

- **union + adjacency, no gather**: after the one id lexsort,
  duplicate lanes are key-equal, so "my cause is the previous kept
  node" is a shifted key compare against the previous *raw* lane —
  pure elementwise.
- **compaction by binary search, not scatter**: the lanes that need
  real work (run heads, "irregular" lanes) are pulled into K static
  slots by searching the cumulative count for 1..K — K log N gathered
  elements instead of an N-wide scatter.
- **cause resolution at K**: only irregular lanes binary-search the
  sorted id lanes ((hi, lo) pair compares in int32 — no int64 needed),
  instead of sort-joining all 2M records.
- **host jumps at K**: the first-non-special-ancestor walk steps
  through a ``back1`` table built elementwise (+ K sparse updates),
  iterating only as deep as real special chains go (1-3 links),
  with query width K.
- **expansion by delta-cumsum, no gather**: per-run preorder bases
  become deltas between lane-consecutive runs (K-wide), scattered to
  K head lanes and cumsum'd — the rank of every lane materializes
  from one full-width cumsum.
- **visibility by direction-flipped scans**: "is my weave successor a
  hide targeting me" splits into the in-run case (a reversed
  forward-fill — elementwise) and the run-tail case (K-wide preorder
  successor lookup).

Semantics are identical to ``jaxw.linearize``/``linearize_v2`` (the
port-of-record pure weaver remains the oracle; parity is fuzz-tested).
Like v2 it needs a static run budget ``k_max`` and reports overflow;
unlike v2, a tree where a node's host happens to be its kept-lane
predecessor while its literal cause is a *non-adjacent* special splits
one extra run (a refinement — the preorder is unchanged because any
node with external children is always a run tail).
"""

from __future__ import annotations

import math
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .arrays import I32_MAX, VCLASS_H_HIDE, VCLASS_HIDE
from .jaxw import _euler_rank, _link_children

__all__ = [
    "merge_weave_kernel_v3",
    "batched_merge_weave_v3",
]


def _shift1(x, fill):
    """The previous lane's value (x shifted right by one)."""
    return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])


def merge_weave_kernel_v3(hi, lo, cause_hi, cause_lo, vclass, valid,
                          k_max: int):
    """Union + reweave for one replica set, sparse-irregular style.

    Same contract as ``jaxw.merge_weave_kernel_v2``: inputs are the
    concatenated (hi, lo)/(cause_hi, cause_lo)/vclass/valid lanes of
    any number of id-sorted trees (invalid lanes carry int32 max);
    returns ``(order, rank, visible, conflict, overflow)``.
    """
    N = hi.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    targets = jnp.arange(1, k_max + 1, dtype=jnp.int32)

    # ---- union: one id sort, then everything below is elementwise
    order = jnp.lexsort((lo, hi))
    h, l = hi[order], lo[order]
    ch, cl = cause_hi[order], cause_lo[order]
    vc, va = vclass[order], valid[order]

    prev_h, prev_l = _shift1(h, I32_MAX), _shift1(l, I32_MAX)
    dup = (h == prev_h) & (l == prev_l) & (idx > 0)
    keep = va & ~dup
    conflict = jnp.any(
        dup & va & (
            (ch != _shift1(ch, 0)) | (cl != _shift1(cl, 0))
            | (vc != _shift1(vc, 0))
        )
    )

    cum_keep = jnp.cumsum(keep.astype(jnp.int32))
    kidx = cum_keep - 1
    n_kept = cum_keep[-1]
    is_root = keep & (idx == 0)
    special = keep & (vc > 0)
    rel = keep & ~is_root

    # previous kept lane and its specialness, from ONE packed forward
    # fill: lane*2 | special of the last kept lane at-or-before here
    sp_pack = lax.cummax(
        jnp.where(keep, idx * 2 + special.astype(jnp.int32), -1)
    )
    sp_prev = _shift1(sp_pack, -1)
    prev_kept = jnp.where(sp_prev >= 0, sp_prev >> 1, -1)
    prev_kept_special = (sp_prev >= 0) & (sp_prev % 2 == 1)

    # adjacency: my cause id IS the previous lane's id. Duplicate lanes
    # carry the kept head's key, so the raw shift compare equals a
    # compare against the previous *kept* node — no gather.
    adj = rel & (ch == prev_h) & (cl == prev_l) & (prev_kept >= 0)
    # a non-special adjacent to a special still needs a host jump
    host_case = adj & ~special & prev_kept_special
    irregular = rel & (~adj | host_case)

    # ---- compact irregular lanes into K slots via binary search
    ir_cum = jnp.cumsum(irregular.astype(jnp.int32))
    n_irr = ir_cum[-1]
    q_lane = jnp.searchsorted(ir_cum, targets, side="left").astype(jnp.int32)
    q_valid = targets <= jnp.minimum(n_irr, k_max)
    q_c = jnp.clip(q_lane, 0, N - 1)
    q_ch, q_cl = ch[q_c], cl[q_c]
    q_adj = adj[q_c]
    q_prev = prev_kept[q_c]
    q_special = special[q_c]

    # ---- resolve irregular causes: (hi, lo) pair binary search at K
    steps = max(1, math.ceil(math.log2(max(2, N)))) + 1
    def sbody(_, c):
        lo_b, hi_b = c
        mid = (lo_b + hi_b) // 2
        ms = jnp.clip(mid, 0, N - 1)
        less = (h[ms] < q_ch) | ((h[ms] == q_ch) & (l[ms] < q_cl))
        return jnp.where(less, mid + 1, lo_b), jnp.where(less, hi_b, mid)

    # derive the carries from varying data (zeros_like, not zeros) so
    # the binary search traces under shard_map, where a replicated
    # constant carry would clash with the varying output axis
    lo_b, hi_b = lax.fori_loop(
        0, steps, sbody,
        (jnp.zeros_like(q_lane), jnp.full_like(q_lane, N)),
    )
    pos = jnp.clip(lo_b, 0, N - 1)
    found = (h[pos] == q_ch) & (l[pos] == q_cl)
    # a miss is a dangling cause: child of root (v1/v2 clip semantics)
    q_cause = jnp.where(q_adj, q_prev,
                        jnp.where(found, pos, 0)).astype(jnp.int32)

    # ---- host jump at K: walk one-step parents until non-special.
    # back1: glued specials step to their kept predecessor, irregular
    # specials to their resolved cause, non-specials to themselves.
    back1 = jnp.where(special & adj, prev_kept, idx).astype(jnp.int32)
    back1 = back1.at[
        jnp.where(q_valid & q_special, q_lane, N)
    ].set(q_cause, mode="drop")

    def wcond(c):
        host, i = c
        hs = jnp.clip(host, 0, N - 1)
        return (i < N) & jnp.any(q_valid & ~q_special & special[hs])

    def wbody(c):
        host, i = c
        hs = jnp.clip(host, 0, N - 1)
        step = q_valid & ~q_special & special[hs]
        return jnp.where(step, back1[hs], host), i + 1

    host_q, _ = lax.while_loop(wcond, wbody, (q_cause, jnp.int32(0)))
    q_parent = jnp.where(q_special, q_cause, host_q)

    # ---- glue: an adjacent child only glues if its parent has no
    # other (irregular) children; any node with external children is
    # thereby a run tail, so child runs always attach after whole runs
    extra = jnp.zeros(N, jnp.int32).at[
        jnp.where(q_valid, q_parent, N)
    ].add(1, mode="drop")
    ec_pack = lax.cummax(
        jnp.where(keep, idx * 2 + (extra > 0).astype(jnp.int32), -1)
    )
    ec_prev = _shift1(ec_pack, -1)
    prev_kept_contested = (ec_prev >= 0) & (ec_prev % 2 == 1)
    glued = adj & ~host_case & ~prev_kept_contested

    run_start = keep & ~glued
    rs_cum = jnp.cumsum(run_start.astype(jnp.int32))
    run_id = rs_cum - 1
    n_runs = rs_cum[-1]
    overflow = n_runs > k_max

    # ---- compact run heads into K slots
    head_lane = jnp.searchsorted(rs_cum, targets, side="left").astype(
        jnp.int32
    )
    r_valid = targets <= jnp.minimum(n_runs, k_max)
    head_c = jnp.clip(head_lane, 0, N - 1)

    # head parent lane: irregular heads resolved above; the rest are
    # contested-adjacent heads whose parent is their kept predecessor
    parent_full = jnp.full(N, -1, jnp.int32).at[
        jnp.where(q_valid, q_lane, N)
    ].set(q_parent, mode="drop")
    h_parent_lane = jnp.where(
        irregular[head_c], parent_full[head_c],
        jnp.where(adj[head_c], prev_kept[head_c], -1),
    )
    h_parent_lane = jnp.where(r_valid & ~is_root[head_c], h_parent_lane, -1)
    parent_run = jnp.where(
        h_parent_lane >= 0,
        run_id[jnp.clip(h_parent_lane, 0, N - 1)],
        -1,
    ).astype(jnp.int32)

    h_special = special[head_c]
    h_kidx = kidx[head_c]
    nxt_kidx = jnp.concatenate([h_kidx[1:], h_kidx[:1]])  # filler tail
    run_len = jnp.where(
        r_valid,
        jnp.where(targets == n_runs, n_kept - h_kidx, nxt_kidx - h_kidx),
        0,
    ).astype(jnp.int32)

    # ---- contracted sibling sort + Euler ranking, all at K
    parent_sort = jnp.where(r_valid & (parent_run >= 0), parent_run, k_max)
    packed = parent_sort * 2 + (~h_special).astype(jnp.int32)
    sord = jnp.lexsort((-head_c, packed))
    fc, ns = _link_children(sord, parent_sort)
    parent_up = jnp.where(r_valid & (parent_run >= 0), parent_run, -1)
    base, _ = _euler_rank(fc, ns, parent_up, run_len)

    # ---- expansion: per-run bases become deltas between lane-
    # consecutive runs; one cumsum materializes every lane's rank
    delta = jnp.where(
        r_valid, base - jnp.concatenate([jnp.zeros((1,), base.dtype),
                                         base[:-1]]), 0
    )
    delta_n = jnp.zeros(N, jnp.int32).at[
        jnp.where(r_valid, head_c, N)
    ].set(delta.astype(jnp.int32), mode="drop")
    base_ff = jnp.cumsum(delta_n)
    ffh = lax.cummax(jnp.where(run_start, kidx, -1))
    rank = jnp.where(keep, base_ff + (kidx - ffh), N).astype(jnp.int32)

    # ---- visibility: weave successor is a hide targeting me.
    # in-run: the next kept lane is a glued hide (its cause IS me) —
    # a reversed forward-fill, elementwise
    hideish = (vc == VCLASS_HIDE) | (vc == VCLASS_H_HIDE)
    kg = glued & hideish
    rpack = lax.cummax(
        jnp.where(jnp.flip(keep), idx * 2 + jnp.flip(kg).astype(jnp.int32),
                  -1)
    )
    rprev = _shift1(rpack, -1)
    killed_inrun = jnp.flip((rprev >= 0) & (rprev % 2 == 1))

    # run tails: the preorder-successor run's head may hide me (K-wide)
    run_by_pos = jnp.full(N, -1, jnp.int32).at[
        jnp.where(r_valid, jnp.clip(base, 0, N - 1), N)
    ].set(jnp.arange(k_max, dtype=jnp.int32), mode="drop")
    succ_pos = base + run_len
    succ_run = jnp.where(
        r_valid & (succ_pos < n_kept),
        run_by_pos[jnp.clip(succ_pos, 0, N - 1)],
        -1,
    )
    s_c = jnp.clip(
        jnp.where(succ_run >= 0, head_c[jnp.clip(succ_run, 0, k_max - 1)],
                  0),
        0, N - 1,
    )
    s_is_hide = (succ_run >= 0) & (
        (vc[s_c] == VCLASS_HIDE) | (vc[s_c] == VCLASS_H_HIDE)
    )
    # tail of run r = the kept lane before the NEXT run's head (lane
    # order); the last run's tail is the last kept lane overall. One
    # K-wide gather — no search.
    nxt_head = jnp.concatenate([head_c[1:], head_c[:1]])
    tail_lane = jnp.where(
        targets == n_runs,
        jnp.maximum(sp_pack[-1] >> 1, 0),
        prev_kept[jnp.clip(nxt_head, 0, N - 1)],
    ).astype(jnp.int32)
    t_c = jnp.clip(tail_lane, 0, N - 1)
    kill_tail = (
        r_valid & s_is_hide & (ch[s_c] == h[t_c]) & (cl[s_c] == l[t_c])
    )
    killed_tail = jnp.zeros(N, bool).at[
        jnp.where(kill_tail, t_c, N)
    ].set(True, mode="drop")

    visible = (
        keep & (vc == 0) & ~is_root & ~(killed_inrun | killed_tail)
    )
    return order, rank, visible, conflict, overflow


merge_weave_kernel_v3_jit = jax.jit(
    merge_weave_kernel_v3, static_argnames="k_max"
)


@partial(jax.jit, static_argnames="k_max")
def batched_merge_weave_v3(hi, lo, cause_hi, cause_lo, vclass, valid,
                           k_max: int):
    """Sparse-irregular batch: [B, M] lanes -> per-replica weave ranks.
    Same contract as ``jaxw.batched_merge_weave_v2``."""

    def row(h, l, ch, cl, vc, va):
        return merge_weave_kernel_v3(h, l, ch, cl, vc, va, k_max)

    return jax.vmap(row)(hi, lo, cause_hi, cause_lo, vclass, valid)
