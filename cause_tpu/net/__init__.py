"""The partition-tolerant network transport (PR 13, ROADMAP item 4's
cross-host follow-on).

``cause_tpu.serve`` made admission a transport-shaped seam
(``Admission.offer`` → write-ahead journal → bounded queue) but kept
every replica in one process. This package is the wire: long-lived
replication sessions connecting remote producers to a ``SyncService``
across real sockets, designed partition-first — SafarDB's split
(arXiv:2603.08003: host owns admission/ordering, accelerator owns
merge) with the ingest ordering pushed into the network layer
(arXiv:1605.05619):

- :mod:`cause_tpu.net.transport` — framed endpoints over the
  ``sync.send_frame`` CRC framing: unbuffered :class:`FrameStream`
  with read deadlines, seeded-jitter exponential :class:`Backoff`,
  :func:`dial` with the partition chaos hook, and the wire-level
  fault seam (latency / reset / blackhole / dup) applied at the send
  side, post-CRC;
- :mod:`cause_tpu.net.session` — :class:`NetClient`: bounded outbound
  queues with shed evidence, reconnect/backoff, heartbeats, NACK
  backpressure honored, and resumable per-(tenant, site) lamport
  watermarks negotiated at every (re)connect so a healed partition
  ships exactly the missed suffix;
- :mod:`cause_tpu.net.server` — :class:`ReplicationServer`: the
  acceptor that turns inbound frames into ``Admission.offer`` calls,
  NACKs sheds with their ``retry_after_ms`` hints, suppresses
  idempotent re-delivery through the journal-seeded watermark,
  detects + re-acks wire-duplicate frames, and rejects out-of-order
  or tampered frames into the PR-11 offender/quarantine ladder.

Acceptance instrument: ``scripts/net_soak.py`` — loopback clients
under seeded partitions/resets/duplicated frames plus a mid-soak
server crash+restore must reconverge bit-identical to the fault-free
single-process oracle with zero admitted ops lost (``--kind net``
ledger rows: reconnects, duplicates suppressed, partition MTTR,
NACK/backoff histogram).

Importable without jax — the transport is host work by design.
"""

from .transport import Backoff, FrameStream, dial, loopback_pair
from .session import NetClient
from .server import ReplicationServer

__all__ = [
    "Backoff",
    "FrameStream",
    "NetClient",
    "ReplicationServer",
    "dial",
    "loopback_pair",
]
