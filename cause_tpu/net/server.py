"""The serve-side acceptor: inbound replication frames → admission.

One :class:`ReplicationServer` fronts a :class:`~cause_tpu.serve
.service.SyncService` (or anything exposing its ``queue``/``tenants``
surface): it accepts long-lived client connections and turns each
inbound ``delta`` frame into one ``Admission.offer`` call, so the
WHOLE PR-12 refusal ladder speaks wire protocol:

- a shed with ``retry_after_ms`` becomes a ``nack`` frame carrying the
  hint — backpressure propagates to the SENDER instead of ballooning
  the queue (the client honors it before re-offering);
- a poison payload NACKs through the PR-11 offender machinery
  (``sync.note_reject`` → quarantine ladder), and a clean validated
  frame resets the consecutive-reject counter exactly like a sync
  round does (``sync.note_clean`` — wire corruption is transient);
- **idempotent re-delivery is suppressed by the lamport watermark**:
  the server keeps one ``{site: [ts, tx]}`` watermark per tenant —
  seeded from the write-ahead journal (the durable authority for
  everything ever wire-admitted) and advanced on each admission — and
  filters re-delivered ops below it before they reach the queue, so a
  client resending after a lost ack can never double-journal an op
  (``net.dup_ops`` evidence, exact counts);
- **wire-duplicate frames are detected and re-acked**: each connection
  carries a client sequence number; ``seq == last`` re-sends the
  stored reply (at-least-once delivery), ``seq < last`` rejects as
  out-of-order (``net.ooo_frame``) — a chaos-duplicated or reordered
  frame is evidence, never double work;
- a connection silent past the idle deadline closes server-side
  (``net.idle_close``) — heartbeat ``ping`` frames keep a
  healthy-but-quiet client alive and emit the ``net.heartbeat``
  events the default ``absence:net.heartbeat`` live rule watches.

Crash safety: the watermark registry is derived state — a restarted
server reseeds it from the journal the restored service already
replayed, so a crash between admission and ack is healed by the
client's resend landing entirely below the reseeded watermark.

Deferral caveat: the ``defer`` rung parks offers UNADMITTED server
-side and promotes them outside the wire protocol's view, so a
promotion racing a client resend could double-journal (idempotent at
merge, but it would skew the duplicate evidence). Net-facing queues
should disable cold-tenant deferral (``defer_frac=1.0`` — the net
soak's configuration); a ``defer`` outcome still NACKs with the hint.

Stdlib + sync/serde only; importable without jax (admission is host
work — the accelerator never sees a socket).
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional

from .. import obs
from .. import sync
from ..collections import shared as s
from ..obs import xtrace
from . import transport
from .transport import FrameStream

__all__ = ["ReplicationServer"]

_NACK_DEFAULT_RETRY_MS = 250.0


class _Conn:
    __slots__ = ("fs", "peer", "last_seq", "last_reply", "uuids")

    def __init__(self, fs: FrameStream, peer: str):
        self.fs = fs
        self.peer = peer
        self.last_seq = 0
        self.last_reply: Optional[dict] = None
        self.uuids: List[str] = []


class ReplicationServer:
    """See the module docstring. ``start()`` spawns the accept loop;
    every connection gets its own handler thread (admission itself is
    thread-safe — the queue's lock is the serialization point).
    ``port=0`` binds an ephemeral port (read it back from ``.port``)."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 idle_timeout_s: float = transport.DEFAULT_IDLE_TIMEOUT_S,
                 site: str = "net.server"):
        self.service = service
        self.queue = service.queue
        self.idle_timeout_s = float(idle_timeout_s)
        self.site = str(site)
        # per-tenant {site: [ts, tx]} watermarks. RLock: _admit holds
        # it across filter -> offer -> advance (one atomic admission
        # step per frame), and _watermark re-enters it for lazy
        # seeding. A welcome racing an in-flight admission therefore
        # waits for the advance — the returned watermark can never
        # understate what the journal already holds, which is the
        # "a lost ack can never double-journal" guarantee.
        self._wm: Dict[str, Dict[str, List[int]]] = {}
        self._wm_lock = threading.RLock()
        self._wm_seeded = False
        self._conns: List[_Conn] = []
        self._conns_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._sock = socket.create_server((host, int(port)))
        self._sock.settimeout(0.25)  # accept-loop poll granularity
        self.host = host
        self.port = self._sock.getsockname()[1]
        self.stats = {
            "connections": 0, "frames": 0, "acks": 0, "nacks": 0,
            "admitted_ops": 0, "dup_frames": 0, "dup_ops_suppressed": 0,
            "ooo_frames": 0, "idle_closes": 0, "heartbeats": 0,
            "poison_nacks": 0,
        }
        self._stats_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    def _bump(self, key: str, n: int = 1) -> None:
        """Every stats increment funnels through this lock: handler
        threads race on the counters and the net soak gates EXACT
        counts, so a lost ``+= 1`` (read-modify-write interleave) is
        a test failure, not noise. ``_bump`` takes no other lock, so
        callers may hold ``_wm_lock``/``_conns_lock`` freely."""
        with self._stats_lock:
            self.stats[key] += n

    # ---------------------------------------------------- watermarks

    def _seed_watermarks_locked(self) -> None:
        """Seed EVERY tenant's per-site lamport watermark in ONE pass
        over the write-ahead journal — the durable authority for every
        op ever wire-admitted (the restored service replayed it; the
        running service journaled it before acking). One pass, not one
        per tenant: the first hello after a crash-restore is exactly
        when a per-tenant scan under the lock would freeze admission.
        Sites absent from the journal resolve to "send everything";
        their overlap, if any, is suppressed op-by-op by the same
        watermark filter. Tenants registered later start empty — they
        have no wire history by construction. Called under _wm_lock.

        The journal is duck-typed on the ``iter_from`` contract: the
        PR-12 single-file ``IngestJournal`` and the PR-15 segmented
        ``WriteAheadLog`` both seed here unchanged (the WAL's scan
        spans every live segment in seq order). Segments retired by
        post-checkpoint GC held only ops every tenant has applied AND
        checkpointed, so a watermark seeded from the surviving suffix
        can be conservative (lower) but never wrong: a client that
        re-ships ops from the retired range lands merges that are
        idempotent no-ops on state the packs already carry — the
        fail-safe direction, same as a site with no journal history
        at all."""
        journal = getattr(self.queue, "journal", None)
        tenants = getattr(self.service, "tenants", {})
        if journal is not None:
            for e in journal.iter_from(0):
                uuid = str(e.get("uuid"))
                if uuid not in tenants:
                    continue
                wm = self._wm.setdefault(uuid, {})
                for it in (e.get("items") or ()):
                    try:
                        ts, site_id, tx = it[0]
                    except (TypeError, ValueError, IndexError):
                        continue
                    cur = wm.get(site_id)
                    if cur is None or (int(ts), int(tx)) > (cur[0],
                                                            cur[1]):
                        wm[site_id] = [int(ts), int(tx)]
        self._wm_seeded = True

    def _watermark(self, uuid: str) -> Optional[Dict[str, List[int]]]:
        tenants = getattr(self.service, "tenants", {})
        if uuid not in tenants:
            return None
        with self._wm_lock:
            if not self._wm_seeded:
                self._seed_watermarks_locked()
            wm = self._wm.get(uuid)
            if wm is None:
                wm = {}
                self._wm[uuid] = wm
            return wm

    # ----------------------------------------------------- lifecycle

    def start(self) -> "ReplicationServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="net-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        with self._conns_lock:
            for conn in self._conns:
                conn.fs.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed (stop())
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:  # pragma: no cover
                pass
            sock.settimeout(self.idle_timeout_s)
            fs = FrameStream(sock, site=self.site)
            conn = _Conn(fs, peer=f"{addr[0]}:{addr[1]}")
            with self._conns_lock:
                self._conns.append(conn)
                self._bump("connections")
                n_open = sum(1 for c_ in self._conns
                             if not c_.fs.closed)
            if obs.enabled():
                obs.gauge("net.connections").set(n_open)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name=f"net-conn-{conn.peer}",
                                 daemon=True)
            # prune finished handlers (and their closed conns) so a
            # long-lived server's bookkeeping stays O(open
            # connections), not O(connections ever)
            self._threads = [x for x in self._threads if x.is_alive()]
            with self._conns_lock:
                self._conns = [c_ for c_ in self._conns
                               if not c_.fs.closed]
            self._threads.append(t)
            t.start()

    # ------------------------------------------------------- handler

    def _handle(self, conn: _Conn) -> None:
        fs = conn.fs
        try:
            while not self._stop.is_set():
                try:
                    frame = transport.recv_msg(
                        fs, timeout_s=self.idle_timeout_s)
                except s.CausalError as e:
                    causes = e.info.get("causes", ())
                    if "read-timeout" in causes:
                        # a connection with no frames for the whole
                        # idle deadline is dead weight — heartbeats
                        # keep a healthy client well inside it
                        self._bump("idle_closes")
                        if obs.enabled():
                            obs.counter("net.idle_closes").inc()
                            obs.event("net.idle_close", peer=conn.peer,
                                      idle_s=self.idle_timeout_s)
                    return
                except OSError:
                    return
                op = frame.get("op") if isinstance(frame, dict) else None
                self._bump("frames")
                try:
                    if op == "hello":
                        reply = self._welcome(conn, frame)
                    elif op == "ping":
                        reply = self._pong(conn, frame)
                    elif op == "delta":
                        reply = self._admit(conn, frame)
                    elif op == "bye":
                        return
                    else:
                        # anything else — unknown op, or a frame that
                        # is not even a dict (json.loads can yield any
                        # JSON type) — is protocol garbage: nack it,
                        # never crash the handler at the trust boundary
                        seq = (frame.get("seq", 0)
                               if isinstance(frame, dict) else 0)
                        reply = {"op": "nack", "seq": seq,
                                 "reason": "bad-frame"}
                    if reply is not None:
                        transport.send_msg(fs, reply)
                except s.CausalError:
                    # injected reset on OUR send, or a peer that died
                    # mid-reply: either way this connection is done —
                    # the client's reconnect ladder owns what's next
                    return
        finally:
            fs.close()
            with self._conns_lock:
                n_open = sum(1 for c_ in self._conns
                             if not c_.fs.closed)
            if obs.enabled():
                obs.gauge("net.connections").set(n_open)

    def _welcome(self, conn: _Conn, frame: dict) -> dict:
        uuids = frame.get("uuids")
        uuids = [str(u) for u in uuids] if isinstance(uuids, list) else []
        conn.uuids = uuids
        wm = {}
        unknown = []
        for uuid in uuids:
            w = self._watermark(uuid)
            if w is None:
                unknown.append(uuid)
            else:
                wm[uuid] = {site: list(h) for site, h in w.items()}
        if obs.enabled():
            # net.hello, NOT net.connect: the server answers a hello
            # on every RE-connect too, so counting it as a connect
            # would inflate the client-side connect/reconnect
            # arithmetic the evidence gates read from a shared stream
            obs.counter("net.hellos").inc()
            obs.event("net.hello", peer=conn.peer,
                      client=str(frame.get("client") or ""),
                      tenants=len(wm), unknown=len(unknown))
        reply = {"op": "welcome", "wm": wm, "unknown": unknown}
        if obs.enabled():
            # wall-clock stamp for the client's NTP-style offset
            # estimate (xtrace.clock_sample); obs-off replies stay
            # byte-identical (scripts/obs_off_pin.py)
            reply.update(xtrace.reply_stamp())
        return reply

    def _seq_guard(self, conn: _Conn, seq: int) -> Optional[dict]:
        """The per-connection at-least-once guard, shared by pings
        and deltas (one seq space): a repeated seq is a WIRE
        DUPLICATE — counted, the stored reply re-sent, nothing
        re-done; an older seq is out-of-order — rejected. None means
        the frame is fresh."""
        if seq == conn.last_seq and conn.last_reply is not None:
            self._bump("dup_frames")
            if obs.enabled():
                obs.counter("net.dup_frames").inc()
                obs.event("net.dup_frame", seq=seq, peer=conn.peer)
            return dict(conn.last_reply)
        if seq <= conn.last_seq:
            self._bump("ooo_frames")
            if obs.enabled():
                obs.counter("net.ooo_frames").inc()
                obs.event("net.ooo_frame", seq=seq,
                          last_seq=conn.last_seq, peer=conn.peer)
            return {"op": "nack", "seq": seq, "reason": "out-of-order"}
        return None

    def _pong(self, conn: _Conn, frame: dict) -> dict:
        seq = int(frame.get("seq") or 0)
        guarded = self._seq_guard(conn, seq)
        if guarded is not None:
            return guarded
        self._bump("heartbeats")
        if obs.enabled():
            obs.counter("net.heartbeats").inc()
            obs.event("net.heartbeat", peer=conn.peer, side="server")
        reply = {"op": "pong", "seq": seq}
        if obs.enabled():
            # heartbeat = recurring clock-offset sample (see _welcome)
            reply.update(xtrace.reply_stamp())
        conn.last_seq = seq
        conn.last_reply = dict(reply)
        return reply

    def _nack(self, seq: int, reason: str,
              retry_after_ms: Optional[float] = None,
              uuid: str = "", site: str = "") -> dict:
        self._bump("nacks")
        reply = {"op": "nack", "seq": seq, "reason": reason}
        if retry_after_ms is not None:
            reply["retry_after_ms"] = retry_after_ms
        if obs.enabled():
            obs.counter("net.nacks").inc()
            fields = {"seq": seq, "reason": reason, "uuid": uuid,
                      "site": site}
            if retry_after_ms is not None:
                fields["retry_after_ms"] = retry_after_ms
            obs.event("net.nack", **fields)
        return reply

    def _admit(self, conn: _Conn, frame: dict) -> dict:
        seq = int(frame.get("seq") or 0)
        guarded = self._seq_guard(conn, seq)
        if guarded is not None:
            return guarded
        uuid = str(frame.get("uuid") or "")
        site = str(frame.get("site") or "")
        items = frame.get("nodes")
        conn.last_seq = seq

        def finish(reply: dict) -> dict:
            conn.last_reply = dict(reply)
            return reply

        # --- the trust boundary (validate BEFORE the watermark filter
        # reads ids out of the payload)
        try:
            sync.validate_node_items(items)
            crc = frame.get("crc")
            if crc is not None \
                    and sync.payload_checksum(items) != crc:
                raise s.CausalError(
                    "sync payload rejected",
                    {"causes": {"payload-checksum"},
                     "why": "checksum mismatch"})
            if any(it[0][1] != site for it in items):
                # the protocol ships per-site batches; a frame whose
                # ops claim another site is tampered, not mis-routed
                raise s.CausalError(
                    "sync payload rejected",
                    {"causes": {"payload-invalid"},
                     "why": "op site != frame site"})
        except s.CausalError as e:
            why = next(iter(e.info.get("causes", ("payload-invalid",))))
            self._bump("poison_nacks")
            sync.note_reject(site, uuid=uuid, why=why)
            return finish(self._nack(seq, why, uuid=uuid, site=site))
        # --- trace continuation (PR 19): an obs-on client attached
        # wire contexts; continue each chain with a "recv" hop and
        # hand the trace ids to admission so journal/tick/wave hops
        # stay linked. Garbage ctx degrades to an untraced frame —
        # never an exception on the admission path. Runs AFTER the
        # validate boundary: a poison frame earns no hops.
        traces: List[str] = []
        if obs.enabled():
            raw_ctx = frame.get("ctx")
            if isinstance(raw_ctx, list):
                for c in raw_ctx[:16]:
                    tr, parent = xtrace.continue_from(c)
                    if not tr:
                        continue
                    xtrace.hop("recv", tr, parent=parent,
                               peer=conn.peer, seq=seq, uuid=uuid,
                               site=site)
                    ids = c.get("ids")
                    if isinstance(ids, list):
                        # bind this batch's op ids server-side so the
                        # lag tracer's converged/apply hops and the
                        # op.lag trace field can join (suppressed ids
                        # bind too — harmless, they never re-apply)
                        xtrace.bind_ops(
                            tr, [tuple(i) for i in ids[:64]
                                 if isinstance(i, list)
                                 and len(i) == 3])
                    traces.append(tr)
        # --- idempotent re-delivery: the lamport watermark filter.
        # Filter -> offer -> advance runs ATOMICALLY under the
        # watermark lock: a client that reconnects while an old
        # handler thread sits between the journal append and the
        # advance must not be handed a stale welcome watermark and
        # re-ship ops the journal already holds (double-journaled —
        # idempotent at merge, but it would corrupt the duplicate
        # evidence and the oracle's entry count). Lock order is
        # _wm_lock -> queue lock; nothing takes them in reverse.
        with self._wm_lock:
            wm = self._watermark(uuid)
            if wm is None:
                return finish(self._nack(seq, "unknown-tenant",
                                         uuid=uuid, site=site))
            horizon = wm.get(site)
            h = (horizon[0], horizon[1]) if horizon else (-1, -1)
            kept = [it for it in items
                    if (int(it[0][0]), int(it[0][2])) > h]
            suppressed = len(items) - len(kept)
            if suppressed:
                self._bump("dup_ops_suppressed", suppressed)
                if obs.enabled():
                    obs.counter("net.dup_suppressed").inc(suppressed)
                    obs.event("net.dup_ops", ops=suppressed,
                              uuid=uuid, site=site, seq=seq)
            if not kept:
                sync.note_clean(site)
                self._bump("acks")
                return finish({"op": "ack", "seq": seq, "admitted": 0,
                               "dup": suppressed})
            adm = self.queue.offer(uuid, site, kept,
                                   traces=traces or None)
            if adm.admitted:
                last = kept[-1][0]
                wm[site] = [int(last[0]), int(last[2])]
        if adm.admitted:
            sync.note_clean(site)
            self._bump("acks")
            self._bump("admitted_ops", len(kept))
            if obs.enabled():
                obs.counter("net.admitted_ops").inc(len(kept))
            return finish({"op": "ack", "seq": seq,
                           "admitted": len(kept), "dup": suppressed})
        # a refusal at any rung becomes a wire NACK carrying the
        # backpressure hint — overload flows back to the sender
        retry = adm.retry_after_ms
        if retry is None and adm.rung in ("reject", "defer"):
            retry = _NACK_DEFAULT_RETRY_MS
        return finish(self._nack(seq, adm.reason or adm.rung,
                                 retry_after_ms=retry,
                                 uuid=uuid, site=site))
