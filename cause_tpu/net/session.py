"""Resumable client replication sessions (the producer side of PR 13).

A :class:`NetClient` is the thin front-end a real fleet runs millions
of: it mints per-site op batches locally, queues them in a BOUNDED
outbound buffer, and ships them to a :class:`~cause_tpu.net.server
.ReplicationServer` over a long-lived framed connection — designed so
that every network failure degrades to *queued outbound deltas*,
never a wedge or an exception on the caller's loop:

- **reconnect/backoff** — a dead peer (reset, blackhole'd reply, read
  deadline, refused dial) marks the session disconnected and arms the
  seeded-jitter exponential backoff ladder; ``pump()`` keeps
  returning immediately (queuing locally) until the next dial is due;
- **resumable watermarks** — every (re)connect negotiates
  ``hello``/``welcome``: the server answers with its per-(tenant,
  site) lamport watermarks, and the client drops queued ops at or
  below them — so a partition heals by shipping EXACTLY the missed
  suffix (ops admitted before the link died are never re-sent, ops
  the server never saw all are). Anything that still overlaps (an ack
  lost in flight) is suppressed op-exactly by the server's watermark
  filter;
- **backpressure honored** — a ``nack`` with ``retry_after_ms`` parks
  the whole session until the hint elapses (one NACK histogram
  bucket per reason), so server overload propagates to the producer
  instead of turning into a hot retry loop;
- **bounded outbound** — ``queue_ops`` refuses past
  ``max_pending_ops`` with an evidenced ``net.shed`` (rung
  ``client-overflow``), the client-side twin of the server's shed
  ladder: a partitioned producer's memory is a declared policy too;
- **heartbeats** — an idle connected session pings inside the
  server's idle deadline, emitting the ``net.heartbeat`` evidence the
  ``absence:net.heartbeat:<t>`` live rule watches.

Protocol is strictly request-response per frame (send one ``delta``,
read replies until the matching seq — stale re-acks from wire
-duplicated frames are drained and counted), which keeps the client a
single-threaded state machine the soak can drive from one thread per
client.

Stdlib + sync/serde only; importable without jax.
"""

from __future__ import annotations

import time
import zlib
from typing import Dict, List, Optional, Tuple

from .. import chaos as _chaos
from .. import obs
from .. import serde
from .. import sync
from ..collections import shared as s
from ..obs import xtrace
from . import transport
from .transport import Backoff, FrameStream

__all__ = ["NetClient"]

# how many stale (lower-seq) replies to drain while waiting for the
# matching one before declaring the connection desynced
_STALE_REPLY_MAX = 64


class NetClient:
    """See the module docstring. Single-threaded: call :meth:`pump`
    from one driving loop (it never raises for network reasons and
    never blocks past the read deadline)."""

    def __init__(self, host: str, port: int, uuids,
                 client_id: str = "",
                 max_pending_ops: int = 4096,
                 backoff: Optional[Backoff] = None,
                 read_timeout_s: float = 5.0,
                 heartbeat_s: float = 2.0,
                 connect_timeout_s: float = 2.0,
                 site: str = "net.client"):
        self.host = host
        self.port = int(port)
        self.uuids = [str(u) for u in uuids]
        self.client_id = str(client_id) or f"client-{port}"
        self.max_pending_ops = int(max_pending_ops)
        self.read_timeout_s = float(read_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.site = str(site)
        self.backoff = backoff or Backoff(
            seed=zlib.crc32(self.client_id.encode()))
        # (uuid, site) -> ordered op triples [(id, cause, value)]
        self._pending: Dict[Tuple[str, str], List[tuple]] = {}
        # (uuid, site) -> [(trace_id, [op ids])] for still-pending
        # batches (PR 19; populated only while obs is on — obs-off
        # ships byte-identical frames, see scripts/obs_off_pin.py)
        self._pending_traces: Dict[Tuple[str, str], List[tuple]] = {}
        self._pending_ops = 0
        self._server_wm: Dict[str, Dict[str, list]] = {}
        self._fs: Optional[FrameStream] = None
        self._seq = 0
        self._not_before = 0.0     # NACK backpressure (monotonic)
        self._next_dial = 0.0      # backoff gate (monotonic)
        self._down_since: Optional[float] = None
        self._last_io = 0.0
        self._last_hb = 0.0
        self.partition_mttr_s: List[float] = []
        self.stats = {
            "connects": 0, "reconnects": 0, "dial_failures": 0,
            "sent_frames": 0, "acked_ops": 0, "dup_acked_ops": 0,
            "resumed_skipped_ops": 0,
            "stale_replies": 0, "heartbeats": 0, "shed_ops": 0,
            "nacks": {}, "backoff_hist": {}, "disconnects": 0,
        }

    # ------------------------------------------------------- produce

    @property
    def outbound_depth(self) -> int:
        return self._pending_ops

    @property
    def connected(self) -> bool:
        return self._fs is not None and not self._fs.closed

    def queue_ops(self, uuid: str, site: str, triples) -> bool:
        """Queue one site's op batch for shipment. Bounded: past
        ``max_pending_ops`` the offer is REFUSED with an evidenced
        ``net.shed`` — during a long partition the producer's memory
        is a declared policy, not an accident. Refused ops were never
        queued (the caller may retry after the link heals)."""
        triples = list(triples)
        if not triples:
            return True
        if self._pending_ops + len(triples) > self.max_pending_ops:
            self.stats["shed_ops"] += len(triples)
            if obs.enabled():
                obs.counter("net.client_shed_ops").inc(len(triples))
                obs.event("net.shed", rung="client-overflow",
                          client=self.client_id, uuid=str(uuid),
                          site=str(site), ops=len(triples),
                          depth=self._pending_ops)
            return False
        key = (str(uuid), str(site))
        self._pending.setdefault(key, []).extend(triples)
        self._pending_ops += len(triples)
        if obs.enabled():
            obs.gauge(f"net.outbound_depth.{self.client_id}").set(
                self._pending_ops)
            # mint the batch's causal identity at the producer: one
            # trace per queued batch, root "mint" hop, op ids bound
            # for the lag→journey drill-down
            trace = xtrace.new_trace()
            xtrace.hop("mint", trace, parent="",
                       client=self.client_id, uuid=str(uuid),
                       site=str(site), ops=len(triples))
            op_ids = [t[0] for t in triples]
            xtrace.bind_ops(trace, op_ids)
            self._pending_traces.setdefault(key, []).append(
                (trace, op_ids))
        return True

    # ------------------------------------------------------ plumbing

    def _now(self) -> float:
        return time.monotonic()

    def _disconnect(self, reason: str) -> None:
        if self._fs is not None:
            self._fs.close()
            self._fs = None
        now = self._now()
        if self._down_since is None:
            self._down_since = now
        delay_ms = self.backoff.next_ms()
        self._next_dial = now + delay_ms / 1000.0
        # pow2-bucketed backoff histogram (the soak's ledger evidence)
        bucket = 1
        while bucket < delay_ms:
            bucket *= 2
        key = f"<{bucket}ms"
        self.stats["backoff_hist"][key] = \
            self.stats["backoff_hist"].get(key, 0) + 1
        self.stats["disconnects"] += 1
        if obs.enabled():
            obs.counter("net.disconnects").inc()
            obs.event("net.disconnect", client=self.client_id,
                      reason=reason,
                      backoff_ms=round(delay_ms, 3),
                      outbound=self._pending_ops)

    def _connect(self) -> None:
        """Dial + hello/welcome + watermark resume. Raises CausalError
        on failure (the pump catches and schedules the backoff)."""
        fs = transport.dial(self.host, self.port, site=self.site,
                            connect_timeout_s=self.connect_timeout_s,
                            read_timeout_s=self.read_timeout_s)
        t0_us = time.time_ns() // 1000
        transport.send_msg(fs, {"op": "hello",
                                "client": self.client_id,
                                "uuids": self.uuids})
        welcome = transport.recv_msg(fs,
                                     timeout_s=self.read_timeout_s)
        t1_us = time.time_ns() // 1000
        if obs.enabled():
            # the welcome is a request/response pair with a server
            # wall-clock stamp (obs-on servers only): one NTP-style
            # clock-offset sample per (re)connect for journey's
            # cross-host ordering
            xtrace.clock_sample(welcome if isinstance(welcome, dict)
                                else {}, t0_us, t1_us, via="hello")
        if not (isinstance(welcome, dict)
                and welcome.get("op") == "welcome"
                and isinstance(welcome.get("wm"), dict)):
            fs.close()
            raise s.CausalError(
                "net: malformed welcome",
                {"causes": {"bad-frame"}, "expected": "welcome"})
        self._fs = fs
        self._server_wm = {
            str(u): {str(st): [int(h[0]), int(h[1])]
                     for st, h in (w or {}).items()}
            for u, w in welcome["wm"].items()}
        self._seq = 0  # seq is per-connection (the server's _Conn)
        reconnect = self.stats["connects"] > 0
        self.stats["connects"] += 1
        if reconnect:
            self.stats["reconnects"] += 1
        now = self._now()
        self._last_io = now
        self._last_hb = now  # heartbeat cadence starts at connect
        mttr = None
        if self._down_since is not None:
            mttr = now - self._down_since
            self.partition_mttr_s.append(mttr)
            self._down_since = None
        self.backoff.reset()
        # resume: drop queued ops the server already admitted — the
        # missed suffix is what remains, and ONLY that ships
        skipped = self._resume_filter()
        if obs.enabled():
            name = "net.reconnect" if reconnect else "net.connect"
            fields = {"client": self.client_id, "side": "client",
                      "resumed_skipped_ops": skipped,
                      "outbound": self._pending_ops}
            if mttr is not None:
                fields["mttr_ms"] = round(mttr * 1000.0, 3)
            obs.counter("net.reconnects" if reconnect
                        else "net.connects").inc()
            obs.event(name, **fields)

    def _resume_filter(self) -> int:
        skipped = 0
        for (uuid, site_id), ops in list(self._pending.items()):
            wm = (self._server_wm.get(uuid) or {}).get(site_id)
            if not wm:
                continue
            h = (int(wm[0]), int(wm[1]))
            fresh = [t for t in ops
                     if (int(t[0][0]), int(t[0][2])) > h]
            dropped = len(ops) - len(fresh)
            if dropped:
                skipped += dropped
                self._pending_ops -= dropped
                if fresh:
                    self._pending[(uuid, site_id)] = fresh
                else:
                    del self._pending[(uuid, site_id)]
                    # batch fully resumed away: the server admitted
                    # it before the link died — its journey continues
                    # from the server-side hops, nothing left to ship
                    self._pending_traces.pop((uuid, site_id), None)
        if skipped:
            self.stats["resumed_skipped_ops"] += skipped
            if obs.enabled():
                obs.gauge(f"net.outbound_depth.{self.client_id}").set(
                self._pending_ops)
        return skipped

    def _recv_matching(self, seq: int) -> dict:
        """Read replies until the one matching ``seq`` (draining and
        counting stale re-acks from wire-duplicated frames)."""
        for _ in range(_STALE_REPLY_MAX):
            reply = transport.recv_msg(self._fs,
                                       timeout_s=self.read_timeout_s)
            if not isinstance(reply, dict):
                break
            if int(reply.get("seq") or 0) == seq:
                return reply
            self.stats["stale_replies"] += 1
        if obs.enabled():
            obs.counter("net.desyncs").inc()
            obs.event("net.desync", expected=seq,
                      drained=_STALE_REPLY_MAX)
        raise s.CausalError(
            "net: reply stream desynced",
            {"causes": {"bad-frame"}, "expected": f"seq {seq}"})

    # ----------------------------------------------------------- pump

    def pump(self, max_batches: Optional[int] = None) -> dict:
        """Drive the session one step: (re)connect when due, ship up
        to ``max_batches`` pending per-site batches (each one framed,
        CRC-tagged, acked synchronously), heartbeat when idle. Network
        failure of ANY kind degrades to the queued state + backoff —
        this method never raises for network reasons and never blocks
        longer than one read deadline."""
        now = self._now()
        if not self.connected:
            if now < self._next_dial:
                return self.status()
            try:
                self._connect()
            except (s.CausalError, OSError) as e:
                self.stats["dial_failures"] += 1
                reason = "net-unreachable"
                if isinstance(e, s.CausalError):
                    reason = next(iter(e.info.get(
                        "causes", ("net-unreachable",))))
                self._disconnect(reason)
                return self.status()
        sent = 0
        try:
            if now >= self._not_before:  # honoring a NACK's retry hint
                for (uuid, site_id) in list(self._pending):
                    if max_batches is not None and sent >= max_batches:
                        break
                    if not self._ship(uuid, site_id):
                        break  # NACK parked the session
                    sent += 1
            if (self.connected
                    and self._now() - self._last_hb >= self.heartbeat_s):
                # unconditional keepalive cadence (busy, idle, or
                # NACK-parked): the absence:net.heartbeat live rule
                # reads this evidence, and a long retry_after_ms hint
                # must not let the server idle-close a healthy,
                # merely-backpressured session
                self._heartbeat()
        except (s.CausalError, OSError) as e:
            reason = "io-error"
            if isinstance(e, s.CausalError):
                reason = next(iter(e.info.get("causes", ("io-error",))))
            self._disconnect(reason)
        return self.status()

    def _ship(self, uuid: str, site_id: str) -> bool:
        """Frame + send + await ack for one (tenant, site) batch.
        Returns False when a NACK parked the session (retry later);
        raises CausalError on transport failure (pump handles)."""
        ops = self._pending.get((uuid, site_id))
        if not ops:
            return True
        enc = serde.encode_node_items(
            {t[0]: (t[1], t[2]) for t in ops})
        crc = sync.payload_checksum(enc)
        if _chaos.enabled():
            # the payload chaos seam, post-CRC — exactly where a real
            # link corrupts (the server's validate boundary detects).
            # Site scoped per client so a committed plan can target
            # one client's stream deterministically; a bare
            # "net.delta" spec still matches via the prefix rule
            enc = _chaos.mangle_items(enc,
                                      f"net.delta.{self.client_id}")
        self._seq += 1
        seq = self._seq
        frame = {"op": "delta", "seq": seq, "uuid": uuid,
                 "site": site_id, "nodes": enc, "crc": crc}
        if obs.enabled():
            # one "send" hop per coalesced batch in this frame; the
            # frame carries their contexts so the server continues
            # the chain with "recv". A retransmit (blackhole, ack
            # lost) emits fresh send hops on the SAME traces — the
            # retry is journey-visible. Obs-off: no ctx key, frame
            # bytes pinned (scripts/obs_off_pin.py).
            ctxs = []
            for tr, op_ids in self._pending_traces.get(
                    (uuid, site_id), ()):
                span = xtrace.hop("send", tr, client=self.client_id,
                                  seq=seq, uuid=uuid, site=site_id,
                                  ops=len(ops))
                ctx = xtrace.wire_context(tr, span)
                if ctx:
                    # the batch's op ids ride along so the SERVER can
                    # bind ops→trace in its own registry (the lag→
                    # journey drill-down is server-side)
                    ctx["ids"] = [list(i) for i in op_ids[:64]]
                    ctxs.append(ctx)
            if ctxs:
                frame["ctx"] = ctxs
        self.stats["sent_frames"] += 1
        if not transport.send_msg(self._fs, frame):
            # blackhole: the frame "went out" but never arrives; the
            # matching-reply read below times out and the session
            # reconnects — behave exactly like a real silent drop
            pass
        self._last_io = self._now()
        reply = self._recv_matching(seq)
        op = reply.get("op")
        if op == "ack":
            self._pending_ops -= len(ops)
            self._pending.pop((uuid, site_id), None)
            self._pending_traces.pop((uuid, site_id), None)
            self.stats["acked_ops"] += int(reply.get("admitted") or 0)
            # ops the server suppressed as re-delivery (a lost ack's
            # resend): cleared from pending too, accounted separately
            # so minted == acked + dup_acked + resumed_skipped holds.
            # (No client-side watermark bookkeeping here: _server_wm
            # is rebuilt wholesale from the next welcome, which is
            # its only reader's input — the server owns the horizon.)
            self.stats["dup_acked_ops"] += int(reply.get("dup") or 0)
            if obs.enabled():
                obs.gauge(f"net.outbound_depth.{self.client_id}").set(
                self._pending_ops)
            return True
        if op == "nack":
            reason = str(reply.get("reason") or "nack")
            self.stats["nacks"][reason] = \
                self.stats["nacks"].get(reason, 0) + 1
            retry_ms = reply.get("retry_after_ms")
            retry_s = (float(retry_ms) / 1000.0
                       if isinstance(retry_ms, (int, float))
                       else _no_hint_retry_s(reason))
            self._not_before = self._now() + retry_s
            if obs.enabled():
                obs.counter("net.client_nacks").inc()
            return False
        raise s.CausalError(
            "net: unexpected reply",
            {"causes": {"bad-frame"}, "got": str(op)})

    def _heartbeat(self) -> None:
        self._seq += 1
        t0_us = time.time_ns() // 1000
        transport.send_msg(self._fs, {"op": "ping", "seq": self._seq})
        reply = self._recv_matching(self._seq)
        if obs.enabled():
            # every heartbeat refreshes the clock-offset estimate
            # (pong carries ts_us/pid from obs-on servers)
            xtrace.clock_sample(reply, t0_us,
                                time.time_ns() // 1000, via="ping")
        if reply.get("op") != "pong":
            raise s.CausalError(
                "net: unexpected heartbeat reply",
                {"causes": {"bad-frame"}, "got": str(reply.get("op"))})
        self._last_io = self._now()
        self._last_hb = self._last_io
        self.stats["heartbeats"] += 1
        if obs.enabled():
            obs.counter("net.heartbeats").inc()
            obs.event("net.heartbeat", client=self.client_id,
                      side="client")

    def flush(self, timeout_s: float = 30.0,
              poll_s: float = 0.01) -> bool:
        """Pump until the outbound queue is empty (True) or the
        deadline passes (False) — the soak's end-of-run drain."""
        deadline = self._now() + float(timeout_s)
        while self._pending_ops and self._now() < deadline:
            self.pump()
            if self._pending_ops:
                time.sleep(poll_s)
        return self._pending_ops == 0

    def close(self) -> None:
        if self.connected:
            try:
                transport.send_msg(self._fs, {"op": "bye"})
            except (s.CausalError, OSError):
                pass
            self._fs.close()
        self._fs = None

    def status(self) -> dict:
        return {"connected": self.connected,
                "outbound_ops": self._pending_ops,
                "connects": self.stats["connects"],
                "reconnects": self.stats["reconnects"],
                "acked_ops": self.stats["acked_ops"],
                "nacks": dict(self.stats["nacks"])}


def _no_hint_retry_s(reason: str) -> float:
    """A NACK without a hint still parks the session briefly — a hot
    retry loop against an overloaded server is the exact failure mode
    the hint exists to prevent. Poison rejects retry sooner (wire
    corruption is transient; the payload at source is clean)."""
    if reason in ("payload-invalid", "payload-checksum"):
        return 0.01
    return 0.1
