"""Framed socket endpoints for the replication transport (PR 13).

The frame protocol already "runs over sockets, pipes, files"
(``cause_tpu.sync``'s length-prefixed JSON frames with CRC-tagged node
payloads); this module supplies the missing transport half — the
pieces a LONG-LIVED cross-host connection needs that a one-shot
``sync_stream`` round does not:

- :class:`FrameStream` — an UNBUFFERED duplex adapter over a connected
  socket, exposing exactly the ``read/write/flush`` surface
  ``sync.send_frame``/``recv_frame`` consume plus ``settimeout`` (the
  read-deadline hook ``sync._arm_deadline`` duck-types against).
  Unbuffered on purpose: a buffered ``makefile()`` reader can pull
  bytes of the NEXT frame into its private buffer, which breaks any
  fd-level deadline machinery; one ``recv`` per read keeps the kernel
  buffer the single source of truth;
- :func:`send_msg` / :func:`recv_msg` — one frame each way with the
  wire-level chaos seam applied at the send side (injected latency,
  connection reset, blackhole, frame duplication — exactly where a
  real link misbehaves, after the CRC was computed over the true
  payload) and read deadlines mapped to the protocol's uniform
  ``read-timeout`` CausalError;
- :class:`Backoff` — seeded-jitter exponential reconnect backoff: the
  delay ladder doubles to a cap and each step is jittered by a
  ``random.Random(seed)`` stream, so (seed → identical backoff
  schedule) holds for the chaos soak's repro contract while a real
  fleet's reconnect storms still decorrelate;
- :func:`dial` — connect with the ``partition`` chaos hook at the one
  place a partition manifests (the connect attempt), mapping every
  refused/unreachable outcome to a uniform ``net-unreachable``
  CausalError the caller's backoff ladder owns.

Stdlib + ``cause_tpu.sync``/``chaos`` only — the transport is host
work by design and must import without jax (the obs rule).
"""

from __future__ import annotations

import random
import socket
import time
from typing import Optional, Tuple

from .. import chaos as _chaos
from .. import sync
from ..collections import shared as s

__all__ = [
    "FrameStream",
    "Backoff",
    "dial",
    "send_msg",
    "recv_msg",
    "loopback_pair",
]

# transport defaults: a silent peer is declared dead after the read
# deadline; a connection with no frames at all for the idle deadline
# is closed server-side (heartbeats keep a healthy-but-quiet client
# alive well inside it)
DEFAULT_READ_TIMEOUT_S = 10.0
DEFAULT_IDLE_TIMEOUT_S = 30.0
DEFAULT_CONNECT_TIMEOUT_S = 5.0


class FrameStream:
    """Unbuffered duplex stream over a connected socket (see module
    docstring). ``site`` names the chaos injection site for frames
    sent THROUGH this stream (``<site>.send``)."""

    __slots__ = ("sock", "site", "closed")

    def __init__(self, sock: socket.socket, site: str = "net"):
        self.sock = sock
        self.site = str(site)
        self.closed = False

    def settimeout(self, timeout_s: Optional[float]) -> None:
        if not self.closed:
            self.sock.settimeout(timeout_s)

    def read(self, n: int) -> bytes:
        """At most one ``recv`` (short reads are the caller's loop —
        ``sync._read_exact`` accumulates). A reset/closed connection
        reads as EOF (empty bytes): the protocol layer's uniform
        ``eof`` reject is the right shape for a dead peer. A deadline
        expiry propagates as ``TimeoutError`` for ``sync`` to map."""
        if self.closed:
            return b""
        try:
            return self.sock.recv(n)
        except TimeoutError:
            raise
        except OSError:
            return b""

    def write(self, data: bytes) -> int:
        self.sock.sendall(data)
        return len(data)

    def flush(self) -> None:  # the socket has no userspace buffer
        pass

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass


class Backoff:
    """Seeded-jitter exponential backoff: attempt ``k`` waits
    ``min(cap, base * 2^k)`` scaled into ``[1/2, 1)`` by the seeded
    jitter stream. ``reset()`` (on a successful connect) rewinds the
    exponent but NOT the jitter stream — the schedule stays a pure
    function of (seed, sequence of next()/reset() calls), which is the
    determinism the chaos soak replays."""

    __slots__ = ("base_ms", "cap_ms", "attempt", "rng")

    def __init__(self, base_ms: float = 50.0, cap_ms: float = 5000.0,
                 seed: int = 0):
        self.base_ms = float(base_ms)
        self.cap_ms = float(cap_ms)
        self.attempt = 0
        self.rng = random.Random(int(seed) * 1_000_003 + 0x5EED)

    def next_ms(self) -> float:
        """The next delay in milliseconds; advances the ladder."""
        raw = min(self.cap_ms, self.base_ms * (2.0 ** self.attempt))
        self.attempt += 1
        return raw * (0.5 + 0.5 * self.rng.random())

    def reset(self) -> None:
        self.attempt = 0


def dial(host: str, port: int, site: str = "net.client",
         connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
         read_timeout_s: float = DEFAULT_READ_TIMEOUT_S) -> FrameStream:
    """Connect to a replication endpoint. The ``partition`` chaos mode
    fires here — one invocation per attempt, so a plan's ``at``
    schedule refuses exactly the attempts it names — and every
    refused/unreachable/timed-out outcome maps to one uniform
    ``net-unreachable`` CausalError (the caller's backoff ladder does
    not care which errno a partition wears)."""
    if _chaos.enabled() and _chaos.net_partition(site):
        raise s.CausalError(
            "net: connection refused (injected partition)",
            {"causes": {"net-unreachable"}, "site": site,
             "injected": True},
        )
    try:
        sock = socket.create_connection((host, int(port)),
                                        timeout=connect_timeout_s)
    except OSError as e:
        raise s.CausalError(
            "net: peer unreachable",
            {"causes": {"net-unreachable"}, "site": site,
             "errno": getattr(e, "errno", None)},
        ) from None
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:  # pragma: no cover - best-effort (AF_UNIX etc.)
        pass
    sock.settimeout(read_timeout_s)
    return FrameStream(sock, site=site)


def send_msg(fs: FrameStream, obj: dict) -> bool:
    """Send one frame through the wire-level chaos seam. Returns
    whether the frame actually went out (False only for an injected
    blackhole — the caller behaves as if it sent; the missing reply is
    the peer's read deadline's problem, exactly like a real silently
    -dropped packet). An injected reset closes the stream and raises
    the uniform ``net-reset`` CausalError; a real dead peer raises it
    too (one reconnect path for both)."""
    if _chaos.enabled():
        lat_ms = _chaos.net_latency_ms(fs.site)
        if lat_ms:
            time.sleep(lat_ms / 1000.0)
        if _chaos.net_reset(fs.site):
            fs.close()
            raise s.CausalError(
                "net: connection reset (injected)",
                {"causes": {"net-reset"}, "site": fs.site,
                 "injected": True},
            )
        if _chaos.net_blackhole(fs.site):
            return False
        # dup injection targets SEQUENCED frames only: the receiver's
        # duplicate evidence is seq-based, so duplicating a seq-less
        # hello/bye would be an injected-but-uncountable fault (and
        # reconnect hellos would shift the dup schedule under crash
        # timing) — the exact-evidence contract stays exact
        dup = "seq" in obj and _chaos.net_dup(fs.site)
    else:
        dup = False
    try:
        sync.send_frame(fs, obj)
        if dup:
            sync.send_frame(fs, obj)
    except OSError as e:
        fs.close()
        raise s.CausalError(
            "net: connection reset",
            {"causes": {"net-reset"}, "site": fs.site,
             "errno": getattr(e, "errno", None)},
        ) from None
    return True


def recv_msg(fs: FrameStream,
             timeout_s: Optional[float] = None) -> dict:
    """Receive one frame under the read deadline (``sync.recv_frame``
    does the deadline arming and the TimeoutError → ``read-timeout``
    mapping)."""
    return sync.recv_frame(fs, timeout_s=timeout_s)


def loopback_pair(site_a: str = "net.a",
                  site_b: str = "net.b") -> Tuple[FrameStream,
                                                  FrameStream]:
    """A connected FrameStream pair over ``socketpair`` (tests and the
    single-process soak's in-memory endpoints)."""
    sa, sb = socket.socketpair()
    return FrameStream(sa, site=site_a), FrameStream(sb, site=site_b)
