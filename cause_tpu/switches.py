"""The canonical list of trace-time kernel strategy switches.

Read from the environment AT TRACE TIME inside the weave kernels, so
they are part of program identity: every cache key, env scrub, and A/B
config driver must agree on this list or stale programs get served
across configs / TPU pessimizations leak into CPU fallbacks. Import it
— never restate it. Dependency-free on purpose: bench.py's parent
process must be able to read it without importing jax.

Values (all optional; unset = XLA default lowering):
- CAUSE_TPU_SORT:    "bitonic" | "pallas"
- CAUSE_TPU_GATHER:  "rowgather"
- CAUSE_TPU_SEARCH:  "matrix" | "matrix-table"
- CAUSE_TPU_SCATTER: "hint"
"""

TRACE_SWITCHES = (
    "CAUSE_TPU_SORT",
    "CAUSE_TPU_GATHER",
    "CAUSE_TPU_SEARCH",
    "CAUSE_TPU_SCATTER",
)
