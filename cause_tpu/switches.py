"""The canonical list of trace-time kernel strategy switches.

Read from the environment AT TRACE TIME inside the weave kernels, so
they are part of program identity: every cache key, env scrub, and A/B
config driver must agree on this list or stale programs get served
across configs / TPU pessimizations leak into CPU fallbacks. Import it
— never restate it. Dependency-free on purpose: bench.py's parent
process must be able to read it without importing jax.

Values (all optional; unset = XLA default lowering):
- CAUSE_TPU_SORT:    "bitonic" | "pallas" | "matrix"
- CAUSE_TPU_GATHER:  "rowgather"
- CAUSE_TPU_SEARCH:  "matrix" | "matrix-table"
- CAUSE_TPU_SCATTER: "hint"
- CAUSE_TPU_FPHASE:  "pallas" (v5 lane expansion as the fused
  tile-window kernel, weaver/pallas_fphase.py; falls back to the XLA
  form when the concat width is not a multiple of 128)
"""

TRACE_SWITCHES = (
    "CAUSE_TPU_SORT",
    "CAUSE_TPU_GATHER",
    "CAUSE_TPU_SEARCH",
    "CAUSE_TPU_SCATTER",
    "CAUSE_TPU_FPHASE",
)

# CAUSE_TPU_-namespace env vars that are deliberately NOT program
# identity: observability, host-side sampling, and file-location knobs
# whose values never reach a traced program. Every CAUSE_TPU_* read in
# the tree must name a member of exactly one of these two registries —
# causelint (cause_tpu.analysis, rule family TID) fails CI on reads of
# unregistered names, so a typo'd switch can't silently become a
# cache-key-less config axis.
KNOWN_ENV_KNOBS = (
    "CAUSE_TPU_OBS",
    "CAUSE_TPU_OBS_OUT",
    "CAUSE_TPU_OBS_RING",
    "CAUSE_TPU_DEFAULTS_FILE",
    "CAUSE_TPU_NATIVE_CACHE",
    "CAUSE_TPU_BODY_SAMPLE",
    "CAUSE_TPU_LEDGER",
    "CAUSE_TPU_LAG_SLO_MS",
    "CAUSE_TPU_CHAOS",
    "CAUSE_TPU_WAL_FSYNC",
    "CAUSE_TPU_OBS_SHIP",
)

# The XLA-only streaming candidate combination ("beststream"): the
# switch set the harvest ladder digest-gates and certifies, and the
# one bench.py self-selects against when no certified defaults exist
# yet. ONE definition on purpose (module rule: import, never restate —
# a bench.py copy that missed a new strategy would silently A/B a
# different config than harvest certifies). Must never name a
# Mosaic-compiled strategy: round-5 window-1 measured this tunnel's
# compile helper crashing or hanging on every Mosaic program, and a
# hang at the round-end bench costs the driver artifact.
BESTSTREAM_FLIPS = {
    "CAUSE_TPU_SORT": "matrix",
    "CAUSE_TPU_GATHER": "rowgather",
    "CAUSE_TPU_SEARCH": "matrix-table",
    "CAUSE_TPU_SCATTER": "hint",
}

# Per-backend default strategies, applied when the env var is UNSET.
# The chip A/B ladder (scripts/harvest.py) decides what goes here: the
# moment a window certifies a winner (digest-gate MATCH + faster than
# the xla baseline), harvest writes it to _tpu_defaults.json next to
# this module, and every later process ships it as the default —
# VERDICT r4 weak #4 asked for defaults to flip the moment evidence
# exists, without a human in the loop. CPU keeps XLA lowerings: the
# streaming strategies are TPU answers to TPU costs (rowgather is a
# measured ~10x CPU pessimization). The explicit env value "xla"
# forces the XLA-default lowering even where a TPU default is set (so
# A/Bs can still measure the baseline).


def _defaults_path() -> str:
    import os

    # env override for subprocess-level tests (and operators pinning a
    # defaults record explicitly); default: next to this module
    return (os.environ.get("CAUSE_TPU_DEFAULTS_FILE", "").strip()
            or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_tpu_defaults.json"))


def _load_measured(path=None) -> dict:
    """The chip-measured defaults record from _tpu_defaults.json
    (written by scripts/harvest.py's decide_defaults after a measuring
    window). Dependency-free (json + this file's directory); absent or
    corrupt file = empty record, never an error."""
    import json

    path = path or _defaults_path()
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except Exception:  # noqa: BLE001 - missing/corrupt = empty
        return {}


_MEASURED = _load_measured()

TPU_DEFAULTS: dict = {
    k: str(v) for k, v in _MEASURED.get("switches", {}).items()
    if k in TRACE_SWITCHES and v
}


def measured_kernel(default: str = "") -> str:
    """The chip-certified kernel choice ("v5", "v5w", "v5f", ...) from
    the measured-defaults record, or ``default`` when no window has
    certified one yet."""
    v = _MEASURED.get("kernel", "")
    return str(v) if v else default


def raw_key(name: str) -> str:
    """Backend-init-free cache-key value for ``name``: the raw env
    value, with the explicit "xla" sentinel collapsed onto unset ONLY
    for switches without a TPU_DEFAULTS entry (for those, both resolve
    to "" on every backend, so the traced programs are identical). A
    DEFAULTED switch keeps them distinct: unset means "apply the
    default on TPU", "xla" means "force the XLA lowering". Lives here,
    next to resolve(), so the key mapping and the trace-time
    resolution can never drift apart (module rule: import, never
    restate). Sound as a program-cache key because the backend is
    process-constant after init — env -> resolved is one mapping per
    process (ADVICE r4 #2: the key path must never trigger backend
    init, so it cannot call resolve())."""
    import os

    v = os.environ.get(name, "").strip()
    if v == "xla" and name not in TPU_DEFAULTS:
        return ""
    return v


def raw_switch_key() -> tuple:
    """The full program-identity snapshot as a cache-key tuple: one
    ``raw_key`` value per TRACE_SWITCHES member, in registry order.
    EVERY host-side cache of a traced program (benchgen's scalar
    programs, parallel.mesh's sharded steps) must fold this tuple into
    its key, or a switch flip serves a stale program — the round-4/5
    incident class causelint rule TID003 now gates. Backend-init-free
    like raw_key itself."""
    return tuple(raw_key(k) for k in TRACE_SWITCHES)


def resolve(name: str) -> str:
    """The effective strategy for ``name`` at trace time: the env var
    if set ("xla" = force the XLA-default lowering), else the
    backend's default. Reads the default backend, so call it only
    inside traced/jitted code paths where backend init is already
    acceptable (all current callers are kernel-trace sites)."""
    import os

    v = os.environ.get(name, "").strip()
    if v:
        return "" if v == "xla" else v
    if not TPU_DEFAULTS:
        return ""
    import jax

    if jax.default_backend() == "tpu":
        return TPU_DEFAULTS.get(name, "")
    return ""
