"""CausalBase — a database of nested causal collections with shared history.

Port of reference src/causal/base/core.cljc: atomic transactions over
multiple collections, EDN-like value flattening (nested dicts/lists
become their own collections referenced by Ref values; strings inside
lists explode to char nodes), a shared lamport clock and site-id, a
sorted history log of reverse-paths, and undo/redo built as *new*
inverting transactions (history stays append-only).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Tuple

from . import util as u
from .collections import ccounter as c_counter
from .collections import clist as c_list
from .collections import cmap as c_map
from .collections import cset as c_set
from .collections import shared as s
from .collections.ccounter import CausalCounter
from .collections.clist import CausalList
from .collections.cmap import CausalMap
from .collections.cset import CausalSet
from .ids import (
    HIDE,
    H_HIDE,
    H_SHOW,
    ROOT_ID,
    is_special,
    new_site_id,
    new_uid,
)

__all__ = [
    "Ref",
    "CB",
    "CausalBase",
    "new_cb",
    "new_causal_base",
    "uuid_to_ref",
    "causal_to_ref",
    "is_ref",
    "ref_to_uuid",
    "get_collection_",
    "cb_to_edn",
    "transact_",
    "undo_",
    "redo_",
    "reset_",
    "invert_",
    "invert_path",
    "subhis",
    "tx_id_indexes",
    "get_next_tx_id",
    "expand_reverse_path",
    "reverse_path_to_path",
    "map_to_nodes",
    "list_to_nodes",
    "flatten_value",
]

REF_NS = "causal.collection.ref"


@dataclass(frozen=True)
class Ref:
    """A pointer to a collection inside a CausalBase. The cause_tpu
    analogue of the reference's ref keywords
    ``:causal.collection.ref/<uuid>`` (base/core.cljc:62-74).
    Materializes through the containing base when rendered."""

    uuid: str

    def __repr__(self) -> str:
        return f":{REF_NS}/{self.uuid}"

    def causal_to_edn(self, opts: Optional[dict] = None):
        """Ref deref on render (the Keyword CausalTo extension,
        base/core.cljc:83-90). Without a base in opts the ref passes
        through unchanged. Cyclic refs render as the unexpanded ref at
        the point of recurrence instead of dying with RecursionError —
        the reference leaves this as an open TODO (base/core.cljc:89)."""
        opts = opts or {}
        cb = opts.get("cb")
        if cb is None:
            return self
        stack = opts.get("_ref_stack", frozenset())
        if self.uuid in stack:
            return self  # cycle: stop expanding, keep the pointer
        opts = dict(opts, _ref_stack=stack | {self.uuid})
        return s.causal_to_edn(get_collection_(cb, self), opts)


def uuid_to_ref(uuid: str) -> Ref:
    return Ref(uuid)


def causal_to_ref(causal) -> Ref:
    return Ref(causal.get_uuid())


def is_ref(v) -> bool:
    return type(v) is Ref


def ref_to_uuid(ref) -> str:
    return ref.uuid if type(ref) is Ref else ref


@dataclass(frozen=True)
class CB:
    """The causal-base value (schema at base/core.cljc:21-43):
    shared clock/site, the sorted reverse-path history log, the three
    undo/redo cursors, and the collections map."""

    lamport_ts: int
    uuid: str
    site_id: str
    history: list  # sorted list of (id, uuid) reverse-paths
    first_undo_lamport_ts: Optional[int]
    last_undo_lamport_ts: Optional[int]
    last_redo_lamport_ts: Optional[int]
    root_uuid: Optional[str]
    collections: Dict[str, Any]
    weaver: str = "pure"

    def evolve(self, **kw) -> "CB":
        return replace(self, **kw)


def new_cb(weaver: str = "pure") -> CB:
    """A fresh causal base; note the lamport clock starts at 1
    (base/core.cljc:45-58)."""
    return CB(
        lamport_ts=1,
        uuid=new_uid(),
        site_id=new_site_id(),
        history=[],
        first_undo_lamport_ts=None,
        last_undo_lamport_ts=None,
        last_redo_lamport_ts=None,
        root_uuid=None,
        collections={},
        weaver=weaver,
    )


def get_collection_(cb: CB, uuid_or_ref=None):
    """The collection for a uuid/ref, or the root collection
    (base/core.cljc:76-81)."""
    if uuid_or_ref is None:
        uuid_or_ref = cb.root_uuid
    if uuid_or_ref is None:
        return None
    return cb.collections.get(ref_to_uuid(uuid_or_ref))


def cb_to_edn(cb: CB, opts: Optional[dict] = None):
    """Materialize the root collection, threading the base through opts
    so Refs deref recursively (base/core.cljc:92-96)."""
    opts = dict(opts or {})
    opts["cb"] = cb
    return s.causal_to_edn(get_collection_(cb), opts)


# ------------------------------ Transact ------------------------------


def _is_maplike(v) -> bool:
    """The reference's ``map?`` — CausalMap counts as a map
    (it implements IPersistentMap there)."""
    return isinstance(v, (dict, CausalMap))


def _is_setlike(v) -> bool:
    """Set-shaped values nest as CausalSet collections (beyond the
    reference, which has no set type — README.md:250 roadmap): Python
    set/frozenset literals and CausalSet handles."""
    return isinstance(v, (set, frozenset, CausalSet))


def _is_counterlike(v) -> bool:
    return isinstance(v, CausalCounter)


def _is_seqable(v) -> bool:
    """The reference's ``seqable?`` restricted to the value shapes the
    tx engine understands: strings, sequences, sets, and causal
    collections."""
    return isinstance(v, (str, list, tuple, set, frozenset, dict,
                          CausalList, CausalMap, CausalSet))


def _as_map(v) -> dict:
    return v.causal_to_edn() if isinstance(v, CausalMap) else v


def _as_seq(v):
    return v.causal_to_edn() if isinstance(v, CausalList) else v


def _as_set(v):
    return v.causal_to_edn() if isinstance(v, CausalSet) else v


def new_node(cb: CB, tx_index: Optional[int], cause, value):
    """Mint a local node; returns ``(next_tx_index, node)``
    (base/core.cljc:100-105)."""
    ti = tx_index or 0
    return (
        ti + 1,
        ((cb.lamport_ts, cb.site_id, ti), cause, value),
    )


def insert(cb: CB, uuid: str, nodes) -> CB:
    """Insert a same-tx run of nodes into the collection at ``uuid`` and
    splice their reverse-paths into the sorted history
    (base/core.cljc:107-115)."""
    nodes = list(nodes)
    reverse_paths = [(n[0], uuid) for n in nodes]
    coll = cb.collections[uuid]
    coll = coll.insert(nodes[0], nodes[1:] or None)
    collections = dict(cb.collections)
    collections[uuid] = coll
    history = u.insert_sorted(
        cb.history, reverse_paths[0], next_vals=reverse_paths[1:]
    )
    return cb.evolve(collections=collections, history=history)


def add_collection_of_this_values_type_to_cb(cb: CB, value, is_root: bool = False):
    """Create an empty collection matching the value's shape; returns
    ``(cb, uuid_or_None)`` (base/core.cljc:117-126)."""
    if _is_maplike(value):
        causal = c_map.new_causal_map(weaver=cb.weaver)
    elif _is_setlike(value):
        causal = c_set.new_causal_set(weaver=cb.weaver)
    elif _is_counterlike(value):
        causal = c_counter.new_causal_counter(weaver=cb.weaver)
    elif _is_seqable(value):
        causal = c_list.new_causal_list(weaver=cb.weaver)
    else:
        return cb, None
    uuid = causal.get_uuid()
    collections = dict(cb.collections)
    collections[uuid] = causal
    cb = cb.evolve(collections=collections)
    if is_root:
        cb = cb.evolve(root_uuid=uuid)
    return cb, uuid


def map_to_nodes(cb: CB, tx_index: int, map_value):
    """Flatten a mapping into key-caused nodes; returns
    ``(cb, tx_index, nodes)`` (base/core.cljc:130-138)."""
    nodes = []
    for k, v in _as_map(map_value).items():
        cb, tx_index, flat_v = flatten_value(cb, tx_index, v,
                                             preserve_strings=True)
        tx_index, n = new_node(cb, tx_index, k, flat_v)
        nodes.append(n)
    return cb, tx_index, nodes


def list_to_nodes(cb: CB, tx_index: int, list_value, cause=None):
    """Flatten a sequence into cause-chained nodes; strings explode to
    char nodes inline (base/core.cljc:140-156). Divergence: the
    reference splits per code unit (its char-seq helper is unused and
    ZWJ-broken, util.cljc:94-97); we split into grapheme-ish clusters
    via util.char_seq so combined emoji stay single nodes. Returns
    ``(cb, tx_index, nodes, last_node_id)``."""
    is_string = isinstance(list_value, str)
    value = u.char_seq(list_value) if is_string else _as_seq(list_value)
    nodes = []
    cause = cause if cause is not None else ROOT_ID
    for v in value:
        if not is_string and isinstance(v, str):
            cb, tx_index, more_nodes, cause = list_to_nodes(
                cb, tx_index, v, cause
            )
            nodes.extend(more_nodes)
        else:
            cb, tx_index, flat_v = flatten_value(
                cb, tx_index, v, preserve_strings=is_string
            )
            tx_index, n = new_node(cb, tx_index, cause, flat_v)
            nodes.append(n)
            cause = n[0]
    return cb, tx_index, nodes, cause


def _set_member_key(x):
    """Deterministic sort key for set members across processes: the
    canonical serde encoding where possible (repr of a frozenset is
    hash-seed dependent), else a type-tagged repr."""
    from . import serde  # lazy: serde imports this module

    try:
        return (0, serde.dumps(x))
    except Exception:  # noqa: BLE001 - unencodable: best-effort order
        return (1, type(x).__name__, repr(x))


def set_to_nodes(cb: CB, tx_index: int, set_value, cause=None):
    """Flatten a set-shaped value into cause-chained add-nodes (the
    shape ``CausalSet.add`` mints). Elements stay whole — no string
    explosion; a set of chars is a set of strings — and iterate in a
    deterministic order so replicas flattening equal literals mint
    comparable structures. Members must render hashable: a member that
    would flatten to a nested collection Ref (dict/list/frozenset
    inside a set) is rejected up front — its rendered value could
    never live in the materialized Python set. Returns
    ``(cb, tx_index, nodes, last_id)``.
    """
    nodes = []
    cause = cause if cause is not None else ROOT_ID
    for v in sorted(_as_set(set_value), key=_set_member_key):
        cb, tx_index, flat_v = flatten_value(cb, tx_index, v,
                                             preserve_strings=True)
        if is_ref(flat_v):
            raise s.CausalError(
                "set members must be scalar (a nested collection "
                "cannot render into a set)",
                {"causes": {"unhashable-set-member"},
                 "type": type(v).__name__},
            )
        tx_index, n = new_node(cb, tx_index, cause, flat_v)
        nodes.append(n)
        cause = n[0]
    return cb, tx_index, nodes, cause


def counter_to_nodes(cb: CB, tx_index: int, value, cause=None):
    """One delta node carrying the counter's current value (a nested
    CausalCounter enters the base as its materialized sum — the same
    render-then-rebuild stance the reference takes for nested causal
    collections, base/core.cljc:130-138)."""
    delta = value.value() if isinstance(value, CausalCounter) else value
    cause = cause if cause is not None else ROOT_ID
    if delta == 0:
        return cb, tx_index, [], cause
    tx_index, n = new_node(cb, tx_index, cause, delta)
    return cb, tx_index, [n], n[0]


def flatten_collection(cb: CB, tx_index: int, value, node_fn):
    """Turn a nested collection value into its own collection plus a Ref
    (base/core.cljc:158-164)."""
    cb, uuid = add_collection_of_this_values_type_to_cb(cb, value)
    out = node_fn(cb, tx_index, value)
    cb, tx_index, nodes = out[0], out[1], out[2]
    if nodes:
        cb = insert(cb, uuid, nodes)
    return cb, tx_index, uuid_to_ref(uuid)


def flatten_value(cb: CB, tx_index: int, value, preserve_strings: bool = False):
    """Recursively flatten an EDN-like value (base/core.cljc:166-172,
    extended with the set/counter types the reference only road-maps:
    set literals and CausalSet handles nest as CausalSet collections,
    CausalCounter handles as counter collections — all behind Refs,
    all first-class in history/undo/serde/sync)."""
    if preserve_strings and isinstance(value, str):
        return cb, tx_index, value
    if _is_maplike(value):
        return flatten_collection(cb, tx_index, value, map_to_nodes)
    if _is_setlike(value):
        return flatten_collection(cb, tx_index, value, set_to_nodes)
    if _is_counterlike(value):
        return flatten_collection(cb, tx_index, value, counter_to_nodes)
    if _is_seqable(value):
        return flatten_collection(cb, tx_index, value, list_to_nodes)
    return cb, tx_index, value


def value_to_nodes(cb: CB, tx_index: int, cause, value, causal=None):
    """Nodes for a value merged into an existing collection
    (base/core.cljc:174-182). ``causal`` disambiguates the target type
    when the value shape alone would pick the wrong flattener (a set
    literal into a CausalSet must not explode strings per char)."""
    if _is_maplike(value):
        return map_to_nodes(cb, tx_index, value)
    if isinstance(causal, CausalSet) and (_is_setlike(value)
                                          or _is_seqable(value)):
        if isinstance(value, str):
            members = {value}  # strings are single members, never chars
        elif _is_setlike(value):
            members = value
        else:
            try:
                members = set(_as_seq(value))
            except TypeError:
                raise s.CausalError(
                    "set members must be hashable",
                    {"causes": {"unhashable-set-member"}},
                ) from None
        cb, tx_index, nodes, _ = set_to_nodes(cb, tx_index, members, cause)
        return cb, tx_index, nodes
    if isinstance(causal, CausalCounter) and _is_counterlike(value):
        cb, tx_index, nodes, _ = counter_to_nodes(cb, tx_index, value,
                                                  cause)
        return cb, tx_index, nodes
    if _is_seqable(value):
        cb, tx_index, nodes, _ = list_to_nodes(cb, tx_index, value, cause)
        return cb, tx_index, nodes
    tx_index, n = new_node(cb, tx_index, cause, value)
    return cb, tx_index, [n]


def merge_value_into_parent_collection(cb: CB, uuid, cause, value) -> bool:
    """Should the value's members merge directly into the addressed
    collection rather than nest (base/core.cljc:184-190)? Sets accept
    set-shaped/sequence members; counters accept scalar deltas through
    the plain-node path below instead."""
    causal = cb.collections.get(uuid)
    if cause is None and _is_maplike(value) and isinstance(causal, CausalMap):
        return True
    if (
        not _is_maplike(value)
        and (_is_seqable(value) or _is_setlike(value))
        and isinstance(causal, (CausalList, CausalSet))
    ):
        return True
    if _is_counterlike(value) and isinstance(causal, CausalCounter):
        return True
    return False


def handle_tx_part_value(cb: CB, tx_part, tx_index: int):
    """(base/core.cljc:192-201)"""
    uuid, cause, value = tx_part
    causal = cb.collections.get(uuid)
    if isinstance(causal, CausalSet) and _is_maplike(value):
        # a nested-collection Ref could never render inside the
        # materialized Python set — reject at transact, not at render
        raise s.CausalError(
            "set members must be scalar (a nested collection cannot "
            "render into a set)",
            {"causes": {"unhashable-set-member"}},
        )
    if merge_value_into_parent_collection(cb, uuid, cause, value):
        cb, tx_index, nodes = value_to_nodes(cb, tx_index, cause, value,
                                             causal)
        if nodes:
            cb = insert(cb, uuid, nodes)
        return cb, tx_index
    cb, tx_index, flat_value = flatten_value(
        cb, tx_index, value, preserve_strings=isinstance(causal, CausalMap)
    )
    tx_index, n = new_node(cb, tx_index, cause, flat_value)
    cb = insert(cb, uuid, [n])
    return cb, tx_index


def handle_tx_part_potential_root(cb: CB, tx_part):
    """A tx-part without a uuid creates a new root collection
    (base/core.cljc:203-208)."""
    uuid, _, value = tx_part
    if uuid is not None:
        return cb, uuid
    return add_collection_of_this_values_type_to_cb(cb, value, is_root=True)


def validate_tx_part(cb: CB, tx_part) -> None:
    """(base/core.cljc:210-220)"""
    uuid, _, value = tx_part
    causal = cb.collections.get(uuid) if uuid is not None else None
    if uuid is not None and cb.root_uuid is None:
        raise s.CausalError(
            "Please transact a root collection first by setting uuid and "
            "cause to nil",
            {"value": value},
        )
    if uuid is not None and causal is None:
        raise s.CausalError(
            "Collection with provided uuid not found", {"uuid": uuid}
        )
    if uuid is None and not isinstance(value, (dict, list, tuple, set,
                                               frozenset, CausalList,
                                               CausalMap, CausalSet,
                                               CausalCounter)):
        raise s.CausalError(
            "Root node must satisfy the coll? predicate", {"value": value}
        )


def handle_tx_part(cb: CB, tx_part, tx_index: int):
    """One tx-part: validate, resolve/create the target collection, then
    flatten and insert the value (base/core.cljc:222-230)."""
    validate_tx_part(cb, tx_part)
    cb, uuid = handle_tx_part_potential_root(cb, tx_part)
    _, cause, value = tx_part
    return handle_tx_part_value(cb, (uuid, cause, value), tx_index)


def transact_(cb: CB, tx) -> CB:
    """Apply a transaction ``[(collection_uuid, cause, value), ...]``
    (base/core.cljc:232-252). The lamport clock ticks once per
    transaction; tx-index orders the nodes within it; a successful
    transact clears the undo/redo cursors."""
    tx_index = 0
    for tx_part in tx:
        cb, tx_index = handle_tx_part(cb, tuple(tx_part), tx_index)
    return cb.evolve(
        lamport_ts=cb.lamport_ts + 1,
        first_undo_lamport_ts=None,
        last_undo_lamport_ts=None,
        last_redo_lamport_ts=None,
    )


# ------------------------------ History ------------------------------


@dataclass(frozen=True)
class Path:
    """An expanded history entry: which collection, which node
    (base/core.cljc:21)."""

    uuid: str
    node: tuple


def expand_reverse_path(cb: CB, reverse_path):
    """``(node, collection)`` for a reverse-path (base/core.cljc:260-265)."""
    nid, uuid = reverse_path
    collection = get_collection_(cb, uuid)
    body = collection.get_nodes()[nid]
    return (nid, body[0], body[1]), collection


def reverse_path_to_path(cb: CB, reverse_path) -> Path:
    """(base/core.cljc:267-270)"""
    node, _ = expand_reverse_path(cb, reverse_path)
    return Path(uuid=reverse_path[1], node=node)


def tx_id_indexes(cb: CB, tx_id):
    """``(tx_start_i, tx_end_i)`` of the reverse-paths for a tx-id in the
    history (base/core.cljc:272-291)."""
    if tx_id is None:
        return None, None
    history = cb.history
    tx_start_node_id = tuple(tx_id) + (0,)
    tx_start_i = u.binary_search(
        history,
        tx_start_node_id,
        match_fn=lambda rp, t: rp[0] == t,
        less_than_fn=lambda rp, t: rp[0] < t,
    )
    if not isinstance(tx_start_i, int):
        return tx_start_i, None
    tx_id = tuple(tx_id)
    i = tx_start_i
    while i + 1 < len(history) and history[i + 1][0][:2] == tx_id:
        i += 1
    return tx_start_i, i


_UNSET = object()


def subhis(cb: CB, start_tx_id, end_tx_id=_UNSET):
    """History slice between two tx-ids inclusive; None means open end;
    the 2-arg form slices a single tx (base/core.cljc:293-311)."""
    if end_tx_id is _UNSET:
        end_tx_id = start_tx_id
    history = cb.history
    start_tx_i, end_tx_i = tx_id_indexes(cb, start_tx_id)
    if start_tx_id != end_tx_id:
        _, end_tx_i = tx_id_indexes(cb, end_tx_id)
    if (start_tx_id is not None and start_tx_i is None) or (
        end_tx_id is not None and end_tx_i is None
    ):
        return []  # a named tx-id that isn't in history
    if end_tx_i is not None:
        return history[(start_tx_i or 0): end_tx_i + 1]
    return history[(start_tx_i or 0):]


def invert_path(path: Path):
    """The inverting tx-part for one path (base/core.cljc:313-320):
    hide/h.hide invert to h.show, h.show to h.hide, and a plain value is
    h.hidden *by id*."""
    nid, cause, value = path.node
    if value is HIDE or value is H_HIDE:
        return (path.uuid, cause, H_SHOW)
    if value is H_SHOW:
        return (path.uuid, cause, H_HIDE)
    return (path.uuid, nid, H_HIDE)


def invert_(cb: CB, history_to_invert) -> CB:
    """Invert a slice of history as one new transaction, with as few
    tx-parts as possible (base/core.cljc:322-343): oldest changes
    transact last (winning at equal causes); paths nested under a
    collection that is itself about to be hidden are dropped; only the
    last tx-part per (uuid, cause) is kept."""
    paths = [
        reverse_path_to_path(cb, rp) for rp in reversed(list(history_to_invert))
    ]
    soon_to_be_hidden_uuids = {
        ref_to_uuid(p.node[2]) for p in paths if is_ref(p.node[2])
    }
    not_nested_paths = [
        p for p in paths if p.uuid not in soon_to_be_hidden_uuids
    ]
    tx = [invert_path(p) for p in not_nested_paths]
    deduped = {}
    for tp in tx:
        deduped[(tp[0], tp[1])] = tp
    return transact_(cb, list(deduped.values()))


def reset_(cb: CB, tx_id, site_ids=None):
    """Undo all transactions back to tx-id; with site-ids, only those
    sites' entries (base/core.cljc:345-352). The 2-arg reference form
    returns the history slice (as-is quirk, preserved)."""
    if site_ids is None:
        return subhis(cb, tx_id, None)
    sites = set(site_ids)
    slice_ = [rp for rp in subhis(cb, tx_id, None) if rp[0][1] in sites]
    return invert_(cb, slice_)


def get_next_tx_id(cb: CB, last_undo_or_redo_ts):
    """The tx-id next in line to be undone/redone: the newest local-site
    entry strictly below the cursor (base/core.cljc:354-369).

    The reference slices history to the exact tx (cursor-1, site) —
    sound there because a base's clock only ever advances through local
    transactions, so local tx timestamps are consecutive. Here
    ``sync_base_pair`` fast-forwards the clock past timestamps consumed
    by peers, so the previous local tx can sit at ANY lower ts; scan
    for it instead of assuming cursor-1 (an exact-slice miss silently
    ended the undo chain after one post-sync undo)."""
    limit = last_undo_or_redo_ts
    for rp in reversed(cb.history):
        lamport_ts, site_id = rp[0][0], rp[0][1]
        if limit is not None and lamport_ts >= limit:
            continue
        if site_id == cb.site_id:
            return (lamport_ts, cb.site_id)
    return None


def undo_(cb: CB) -> CB:
    """Undo the next transaction on the local site's undo stack
    (base/core.cljc:375-390). Undo IS a new transaction."""
    next_undo_tx_id = get_next_tx_id(cb, cb.last_undo_lamport_ts)
    if next_undo_tx_id is None:
        return cb
    reverse_paths = [
        rp for rp in subhis(cb, next_undo_tx_id) if rp[0][1] == cb.site_id
    ]
    first_undo = (
        cb.first_undo_lamport_ts
        if cb.first_undo_lamport_ts is not None
        else next_undo_tx_id[0]
    )
    cb = invert_(cb, reverse_paths)
    return cb.evolve(
        first_undo_lamport_ts=first_undo,
        last_undo_lamport_ts=next_undo_tx_id[0],
        last_redo_lamport_ts=None,
    )


def redo_(cb: CB) -> CB:
    """Redo the previously-undone transaction; never redoes past the
    first undo (base/core.cljc:392-409)."""
    next_redo_tx_id = get_next_tx_id(cb, cb.last_redo_lamport_ts)
    first_undo = cb.first_undo_lamport_ts
    last_undo = cb.last_undo_lamport_ts
    if (
        first_undo is None
        or next_redo_tx_id is None
        or next_redo_tx_id[0] <= first_undo
    ):
        return cb
    reverse_paths = [
        rp for rp in subhis(cb, next_redo_tx_id) if rp[0][1] == cb.site_id
    ]
    cb = invert_(cb, reverse_paths)
    return cb.evolve(
        first_undo_lamport_ts=first_undo,
        last_undo_lamport_ts=last_undo,
        last_redo_lamport_ts=next_redo_tx_id[0],
    )


# ------------------------------ CausalBase ------------------------------


class CausalBase:
    """Immutable CausalBase handle (base/core.cljc:415-457)."""

    __slots__ = ("cb",)

    def __init__(self, cb: CB):
        object.__setattr__(self, "cb", cb)

    def __setattr__(self, *a):
        raise AttributeError("CausalBase is immutable")

    # -- CausalBase protocol (protocols.cljc:37-48) --
    def transact(self, tx) -> "CausalBase":
        return CausalBase(transact_(self.cb, tx))

    def get_collection(self, ref_or_uuid=None):
        return get_collection_(self.cb, ref_or_uuid)

    def undo(self) -> "CausalBase":
        return CausalBase(undo_(self.cb))

    def redo(self) -> "CausalBase":
        return CausalBase(redo_(self.cb))

    def set_site_id(self, site_id: str) -> "CausalBase":
        return CausalBase(self.cb.evolve(site_id=site_id))

    # -- CausalMeta --
    def get_uuid(self) -> str:
        return self.cb.uuid

    def get_ts(self) -> int:
        return self.cb.lamport_ts

    def get_site_id(self) -> str:
        return self.cb.site_id

    # -- CausalTo --
    def causal_to_edn(self, opts: Optional[dict] = None):
        return cb_to_edn(self.cb, opts)

    def __eq__(self, other) -> bool:
        return isinstance(other, CausalBase) and self.cb == other.cb

    def __hash__(self) -> int:
        return hash((self.cb.uuid, self.cb.lamport_ts, self.cb.site_id,
                     len(self.cb.history)))

    def __repr__(self) -> str:
        return f"#causal/base {cb_to_edn(self.cb)!r}"


def new_causal_base(weaver: str = "pure") -> CausalBase:
    """Create a new causal base (base/core.cljc:454-457). ``weaver``
    selects the weave backend for every collection it creates."""
    return CausalBase(new_cb(weaver))
