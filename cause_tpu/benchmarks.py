"""The benchmark suite: the BASELINE configs (and a map-fleet
row) as a CLI.

The reference keeps criterium harnesses in REPL comment blocks and
publishes no numbers (reference: test/causal/collections/
list_test.cljc:219-228); the roadmap wants a benchmark/estimator CLI
(README.md:242). cause_tpu ships one: every BASELINE.json config is a
named, runnable benchmark with a JSON-line report, across weave
backends where that makes sense.

    python -m cause_tpu.benchmarks                  # all, default sizes
    python -m cause_tpu.benchmarks -c 1 -w native   # one config/backend
    python -m cause_tpu.benchmarks --profile DIR    # jax.profiler trace
                                                    # around device runs

Configs (BASELINE.json "configs"):
  1 CausalList append-only weave (single site, 1k char insertions)
  2 CausalList 3-site concurrent insert + hide tombstones
  3 CausalMap key overwrite + id-caused undo/redo tombstones
  4 CausalBase nested list-in-map rich-text doc
  5 batched merge of divergent CausalLists (the north-star; device)
  6 map-fleet wave (key-rooted forests; v5 segment-union vs v4; device)
"""

from __future__ import annotations

import argparse
import contextlib
import json
import time
from typing import Callable, Dict, Optional

from . import benchgen
from .cbase import new_causal_base
from .collections.clist import CausalList, new_causal_list
from .collections.cmap import new_causal_map
from .ids import K, new_site_id

__all__ = ["CONFIGS", "run_config", "main"]


def _timed(fn: Callable, reps: int = 3):
    """Best-of-reps wall time (seconds) and the last result."""
    best, out = float("inf"), None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def _text(n: int) -> str:
    """The shared config-1 payload (identical input for every variant)."""
    return ("abcdefgh" * (n // 8 + 1))[:n]


def config1_append_only(weaver: str, n: int = 1000, reps: int = 3) -> dict:
    """Single-site append-only list: n chars conj'd one at a time (the
    typing hot path, reference list.cljc:36-40)."""
    text = _text(n)

    def run():
        cl = new_causal_list(weaver=weaver)
        for ch in text:
            cl = cl.conj(ch)
        return cl

    secs, cl = _timed(run, reps)
    assert len(cl) == n
    return {
        "config": 1,
        "metric": f"append-only conj x{n}",
        "weaver": weaver,
        "value": round(n / secs, 1),
        "unit": "nodes/sec",
    }


def config1_append_lazy(n: int = 1000, reps: int = 3) -> dict:
    """Config 1 in lazy-weave mode: conj n chars with the weave
    deferred (O(1) tail hint + persistent stores), then ONE render at
    the end — the fleet-replica editing profile. The render is inside
    the timed region, so this is the honest type-then-read cost.

    Lazy mode's render is a full rebuild, so it pairs with a fast
    rebuild backend: native (C++ ranks) when available, else the jax
    weaver — lazy+pure would just defer the same O(n^2) fold. Measured
    flat ~20k nodes/s at 1k AND 5k vs eager's degrading ~5-10k."""
    from . import native

    backend = ("native" if native.available() else "jax")
    text = _text(n)

    def run():
        cl = new_causal_list(weaver=backend, lazy=True)
        for ch in text:
            cl = cl.conj(ch)
        if len(cl) != n:  # the read IS the materialization; assert-free
            raise AssertionError(len(cl))  # so -O cannot skip it
        return cl

    secs, _cl = _timed(run, reps)
    return {
        "config": 1,
        "metric": f"lazy conj x{n} + one render",
        "weaver": f"lazy+{backend}",
        "value": round(n / secs, 1),
        "unit": "nodes/sec",
    }


def config1_bulk_extend(weaver: str, n: int = 1000, reps: int = 3) -> dict:
    """Config 1's paste variant: the same n chars as contiguous
    transaction runs via extend — the O(n+m) path (README.md:50,229)."""
    text = _text(n)

    def run():
        return new_causal_list(weaver=weaver).extend(text)

    secs, cl = _timed(run, reps)
    assert len(cl) == n
    return {
        "config": 1,
        "metric": f"bulk extend x{n}",
        "weaver": weaver,
        "value": round(n / secs, 1),
        "unit": "nodes/sec",
    }


def config2_concurrent_hide(weaver: str, n_per_site: int = 120,
                            reps: int = 3) -> dict:
    """3 sites interleave inserts, hide every 5th node, then all three
    pairwise merges converge."""
    import random

    def run():
        rng = random.Random(5)
        base = new_causal_list(*"seed", weaver=weaver)
        sites = [new_site_id() for _ in range(3)]
        replicas = [
            CausalList(base.ct.evolve(site_id=site)) for site in sites
        ]
        for step in range(n_per_site):
            for i, r in enumerate(replicas):
                nodes = list(r.ct.weave)
                cause = rng.choice(nodes)[0]
                ts = r.get_ts() + 1
                nid = (ts, sites[i], 0)
                if step % 5 == 4:
                    from .ids import HIDE

                    r = r.insert((nid, cause, HIDE))
                else:
                    r = r.insert((nid, cause, f"v{step}"))
                replicas[i] = r
        m = replicas[0].merge(replicas[1]).merge(replicas[2])
        return m

    secs, m = _timed(run, reps)
    total_nodes = len(m.ct.nodes)
    return {
        "config": 2,
        "metric": f"3-site concurrent insert+hide, {total_nodes} nodes",
        "weaver": weaver,
        "value": round(total_nodes / secs, 1),
        "unit": "nodes/sec",
    }


def config3_map_undo_redo(weaver: str, n_keys: int = 40,
                          overwrites: int = 6, reps: int = 3) -> dict:
    """Map LWW overwrites plus id-caused h.hide/h.show tombstone churn
    (the map undo-by-id shape, reference map.cljc:283-288)."""
    from .ids import H_HIDE, H_SHOW

    def run():
        cm = new_causal_map(weaver=weaver)
        for k in range(n_keys):
            key = K(f"k{k}")
            for o in range(overwrites):
                cm = cm.assoc(key, f"v{o}")
        # undo/redo the latest overwrite of each key by id
        for node in list(cm):
            nid = node[0]
            cm = cm.append(nid, H_HIDE)
            cm = cm.append(nid, H_SHOW)
        return cm

    secs, cm = _timed(run, reps)
    total = len(cm.ct.nodes)
    return {
        "config": 3,
        "metric": f"map overwrite+undo/redo, {total} nodes",
        "weaver": weaver,
        "value": round(total / secs, 1),
        "unit": "nodes/sec",
    }


def config4_rich_text_base(weaver: str, paragraphs: int = 8,
                           para_len: int = 60, reps: int = 3) -> dict:
    """CausalBase rich-text doc: a map of paragraph-lists of chars, with
    transactions, edits, and undo/redo (the slate-eunoia shape)."""

    from .cbase import is_ref

    def run():
        cb = new_causal_base(weaver=weaver)
        # map of paragraphs; each paragraph is a nested char-list
        doc = {K(f"p{i}"): ["x" * para_len] for i in range(paragraphs)}
        cb = cb.transact([[None, None, doc]])
        root = cb.get_collection()
        # edit every paragraph (one tx each), then undo/redo the last
        for node in list(root):
            if is_ref(node[2]):
                cb = cb.transact([[node[2].uuid, None, "!"]])
        cb = cb.undo()
        cb = cb.redo()
        return cb

    secs, cb = _timed(run, reps)
    total = sum(len(coll.ct.nodes) for coll in cb.cb.collections.values())
    return {
        "config": 4,
        "metric": f"base rich-text doc, {total} nodes",
        "weaver": weaver,
        "value": round(total / secs, 1),
        "unit": "nodes/sec",
    }


def config5_batched_merge(weaver: str = "jax", n_replicas: int = 64,
                          n_base: int = 800, n_div: int = 100,
                          cap: int = 1024, reps: int = 3,
                          k_max: Optional[int] = None,
                          kernel: str = "v4",
                          profile_dir: Optional[str] = None) -> dict:
    """Batched device merge of divergent replicas (north-star shape;
    sizes here are CLI defaults — bench.py runs the full 1024x10k).
    ``k_max``: None = workload-derived run budget, 0 = the uncompressed
    v1 kernel. ``kernel`` picks the compressed kernel ("v5"
    segment-union, "v4" marshal-resolved causes, "v4w" v4 + Pallas
    euler walk, "v3" sparse-irregular, or "v2" chain-compressed); v5
    consumes the LANE_KEYS5 lanes, v4/v4w LANE_KEYS4, the others
    LANE_KEYS. bench.py's ladder tries v5 then v4."""
    import numpy as _np

    import jax

    from .benchgen import (
        LANE_KEYS,
        LANE_KEYS4,
        LANE_KEYS5,
        merge_wave_scalar,
    )

    batch = benchgen.batched_pair_lanes(
        n_replicas=n_replicas, n_base=n_base, n_div=n_div,
        capacity=cap, hide_every=8,
    )
    u_max = 0
    if kernel == "v5" and k_max != 0:
        batch = dict(batch, **benchgen.batched_v5_inputs(batch, cap))
        lane_names = LANE_KEYS5
        u_max = benchgen.v5_token_budget(batch)
        if k_max is None:
            k_max = u_max
    else:
        lane_names = (
            LANE_KEYS4 if (kernel in ("v4", "v4w") and k_max != 0)
            else LANE_KEYS
        )
        if k_max is None:
            k_max = benchgen.pair_run_budget(batch)
    args = [jax.device_put(batch[k]) for k in lane_names]

    def step():
        out = _np.asarray(
            merge_wave_scalar(*args, k_max=k_max, kernel=kernel,
                              u_max=u_max)
        )
        if k_max and out.shape and out[1]:
            raise RuntimeError("run budget overflow — raise k_max")
        return out

    step()  # compile + warm
    ctx = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )
    with ctx:
        secs, _ = _timed(step, reps)
    return {
        "config": 5,
        "metric": f"batched merge, {n_replicas} pairs x "
                  f"{1 + n_base + n_div}-node lists",
        "weaver": f"jax-{kernel}" if k_max else "jax-v1",
        "value": round(secs * 1000.0, 3),
        "unit": "ms",
    }


def config6_map_fleet(weaver: str = "jax", n_pairs: int = 64,
                      n_keys: int = 24, edits: int = 12,
                      reps: int = 3, kernel: str = "v5",
                      profile_dir: Optional[str] = None) -> dict:
    import jax

    """Map-fleet wave: batched merge of CausalMap replica pairs as
    key-rooted forests (round-5 line: the v5 segment-union route makes
    map fleets pay divergence, not node width — ``kernel="v4"``
    measures the full-width route for comparison)."""
    import random as _random

    import cause_tpu as _c
    from .collections.cmap import CausalMap
    from .ids import new_site_id
    from .weaver import mapw

    rng = _random.Random(1234)
    base = _c.cmap()
    for i in range(n_keys):
        base = base.append(_c.K(f"k{i}"), f"v{i}")
    pairs = []
    for p in range(n_pairs):
        a = CausalMap(base.ct.evolve(site_id=new_site_id()))
        b = CausalMap(base.ct.evolve(site_id=new_site_id()))
        for e in range(edits):
            a = a.append(_c.K(f"k{rng.randrange(n_keys + 4)}"),
                         f"a{p}.{e}")
            b = b.append(_c.K(f"k{rng.randrange(n_keys + 4)}"),
                         f"b{p}.{e}")
        pairs.append((a, b))

    def step():
        return mapw.merge_map_wave(pairs, kernel=kernel)

    step()  # compile + warm
    ctx = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )
    with ctx:
        secs, res = _timed(step, reps)
    assert not res.fallback
    return {
        "config": 6,
        "metric": f"map fleet wave, {n_pairs} pairs x "
                  f"~{1 + n_keys + n_keys + edits} nodes",
        "weaver": f"jax-{kernel}",
        "value": round(secs * 1000.0, 3),
        "unit": "ms",
    }


CONFIGS: Dict[int, Callable] = {
    1: config1_append_only,
    2: config2_concurrent_hide,
    3: config3_map_undo_redo,
    4: config4_rich_text_base,
    5: config5_batched_merge,
    6: config6_map_fleet,
}

# configs 1-4 exercise the host path; 5 is device-only
HOST_WEAVERS = ("pure", "native")


def run_config(num: int, weaver: str, profile_dir: Optional[str] = None) -> dict:
    fn = CONFIGS[num]
    if num in (5, 6):
        return fn(profile_dir=profile_dir)
    return fn(weaver)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-c", "--config", type=int, choices=sorted(CONFIGS),
                   help="run one config (default: all)")
    p.add_argument("-w", "--weaver", default=None, choices=HOST_WEAVERS,
                   help="weave backend for host configs")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="write a jax.profiler trace for device configs")
    args = p.parse_args(argv)

    from . import native

    nums = [args.config] if args.config else sorted(CONFIGS)
    for num in nums:
        if num == 5:
            print(json.dumps(run_config(num, "jax", args.profile)))
            continue
        if num == 6:
            # map fleet: the v5 segment-union route and the v4
            # full-width route, side by side
            print(json.dumps(config6_map_fleet(
                kernel="v5", profile_dir=args.profile)))
            print(json.dumps(config6_map_fleet(kernel="v4")))
            continue
        weavers = [args.weaver] if args.weaver else list(HOST_WEAVERS)
        for w in weavers:
            if w == "native" and not native.available():
                print(json.dumps({"config": num, "weaver": "native",
                                  "skipped": "native toolchain unavailable"}))
                continue
            print(json.dumps(run_config(num, w)))
            if num == 1:
                print(json.dumps(config1_bulk_extend(w)))
        if num == 1:
            # backend-independent row (picks native/jax itself)
            print(json.dumps(config1_append_lazy()))


if __name__ == "__main__":
    main()
